"""Device arena ops: scatter-write/gather byte equivalence with a dense cache,
and reorder (spec-decode compaction) semantics.

Ports the intent of /root/reference/tests/test_phase0_cache_write_parity.py
(slab write == torch.cat) and test_paged_kv_spec_dec_routing.py.
"""

import numpy as np

import jax.numpy as jnp

from bloombee_tpu.kv.arena import (
    arena_reorder,
    arena_write,
    gather_pages,
    make_arena,
)
from bloombee_tpu.kv.paged import PagedKVTable


def test_write_then_gather_equals_dense():
    L, P, ps, kv, hd = 2, 8, 4, 2, 8
    arena = make_arena(L, P, ps, kv, hd, dtype=jnp.float32)
    t = PagedKVTable(P, ps)
    t.add_seq(0)
    t.add_seq(1)

    rng = np.random.default_rng(0)
    dense = {0: [], 1: []}
    # interleaved multi-step writes of uneven sizes
    for step, n in enumerate([3, 5, 1]):
        for sid in (0, 1):
            k_new = rng.normal(size=(n, kv, hd)).astype(np.float32)
            v_new = rng.normal(size=(n, kv, hd)).astype(np.float32)
            slots = jnp.asarray(t.assign_write_slots(sid, n))
            for layer in range(L):
                k_l, v_l = arena_write(
                    arena["k"][layer], arena["v"][layer], slots,
                    jnp.asarray(k_new) * (layer + 1), jnp.asarray(v_new),
                )
                arena["k"] = arena["k"].at[layer].set(k_l)
                arena["v"] = arena["v"].at[layer].set(v_l)
            dense[sid].append(k_new)

    pt = jnp.asarray(t.page_table([0, 1], max_pages=3))
    for layer in range(L):
        gathered = np.asarray(gather_pages(arena["k"][layer], pt, ps))
        for i, sid in enumerate((0, 1)):
            ref = np.concatenate(dense[sid], axis=0) * (layer + 1)
            np.testing.assert_array_equal(gathered[i, : len(ref)], ref)


def test_reorder_gathers_before_scatter():
    L, P, ps, kv, hd = 1, 4, 4, 1, 4
    arena = make_arena(L, P, ps, kv, hd, dtype=jnp.float32)
    rows = jnp.arange(P * ps, dtype=jnp.float32)[:, None, None] * jnp.ones(
        (1, kv, hd)
    )
    arena["k"] = arena["k"].at[0].set(rows)
    arena["v"] = arena["v"].at[0].set(rows * 10)

    # overlapping src/dst: move slots [5, 6, 2] onto [2, 3, 4]
    src = jnp.asarray([5, 6, 2])
    dst = jnp.asarray([2, 3, 4])
    k_l, v_l = arena_reorder(arena["k"][0], arena["v"][0], src, dst)
    got = np.asarray(k_l[:, 0, 0])
    # slot 4 must receive the OLD value of slot 2 (gather-before-scatter)
    assert got[2] == 5 and got[3] == 6 and got[4] == 2
    assert np.asarray(v_l[:, 0, 0])[4] == 20
