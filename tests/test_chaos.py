"""Chaos-hardening e2e: deterministic fault injection, deadline aborts,
and graceful drain.

The swarm's whole value proposition is surviving flaky peers; these tests
make the failures *provokable* (wire/faults.py FaultPlan) instead of hoping
a killed process lands on an interesting step. Every fault sequence is
seeded, so a failure reproduces bit-for-bit from the test source alone.
"""

import asyncio
import os
import signal
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.server.compute_queue import PRIORITY_INFERENCE
from bloombee_tpu.swarm.data import ServerState
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_chaos")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    """Every test leaves the process-wide fault plan disarmed."""
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


# ----------------------------------------------------------- fault plan unit
@pytest.mark.chaos
def test_fault_rule_nth_count_and_seeded_prob():
    import random

    rng = random.Random(3)
    rule = FaultRule(site="send", action="delay", method="sitem", nth=2,
                     count=2)
    hdr = {"t": "sitem"}
    # five matches: fires on the 2nd and 3rd only (nth=2, count=2)
    assert [rule.wants("send", None, hdr, rng) for _ in range(5)] == [
        False, True, True, False, False,
    ]
    # wrong site / method never match (and never consume the nth counter)
    assert not rule.wants("read", None, hdr, rng)
    assert not FaultRule(site="send", action="delay", method="req").wants(
        "send", None, hdr, rng
    )
    # probabilistic rules draw from the PLAN's rng: same seed, same faults
    prob = FaultRule(site="send", action="delay", prob=0.5)
    seq_a = [prob.wants("send", None, hdr, random.Random(9))
             for _ in range(1)] + \
            [prob.wants("send", None, hdr, rng) for _ in range(30)]
    assert any(seq_a) and not all(seq_a)
    rng_r1, rng_r2 = random.Random(7), random.Random(7)
    assert [prob.wants("send", None, hdr, rng_r1) for _ in range(30)] == [
        prob.wants("send", None, hdr, rng_r2) for _ in range(30)
    ]


@pytest.mark.chaos
def test_plan_port_targeting_picks_one_peer():
    plan = FaultPlan(seed=1)
    plan.add(FaultRule(site="send", action="reset", method="sitem",
                       port=7001))
    # wrong-port peers never match (and don't consume the rule's counter)
    assert plan._pick("send", ("127.0.0.1", 7002), {"t": "sitem"}) is None
    assert plan._pick("send", ("127.0.0.1", 7001), {"t": "sitem"}) is not None


# ------------------------------------------------------- chaos determinism e2e
@pytest.mark.chaos
def test_chaos_decode_token_identical_to_fault_free(tiny_model_dir):
    """3-server swarm under seeded chaos — delayed frames on the head span,
    a connection reset to the preferred tail on decode step 2, and a real
    mid-decode server kill — must produce token-for-token the fault-free
    greedy decode, with no peer left permanently banned."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2, throughput=10.0)
        s_b = _server(model_dir, rc(), 2, 3, throughput=10.0)  # preferred
        s_c = _server(model_dir, rc(), 2, 3, throughput=1.0)  # backup
        for s in (s_a, s_b, s_c):
            await s.start()

        input_ids = np.arange(5)[None, :] % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        # rule order matters: _pick returns the FIRST match, so the reset
        # (which must count client->s_b frames exactly) goes before the
        # broad delay rule
        plan = FaultPlan(seed=7)
        plan.add(FaultRule(site="send", action="reset", method="sitem",
                           port=s_b.port, nth=2, count=1))
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           port=s_a.port, delay_s=0.02, nth=1, count=3))
        faults.set_plan(plan)

        cfg = ClientConfig(use_push=False, ban_timeout=2.0, ban_max=8.0)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(16, 1)
        await session.__aenter__()
        used = {s.span.server_info.port for s in session._spans}
        assert s_b.port in used  # chaos targets the route actually taken

        ids = await model.generate(input_ids, max_new_tokens=3,
                                   session=session)
        await s_b.stop()  # mid-decode kill (may already be rerouted away)
        more = await model.generate(ids[:, -1:], max_new_tokens=3,
                                    session=session)
        final = np.concatenate([ids, more[:, 1:]], axis=1)
        np.testing.assert_array_equal(final, ref)

        # the injected faults actually landed (a silently inert plan would
        # turn this into a plain failover test)
        actions = {(site, act) for site, act, _ in plan.log}
        assert ("send", "reset") in actions
        assert ("send", "delay") in actions
        # no peer is permanently banned: every ban decays within the
        # backoff cap and is probe-able afterwards
        now = time.monotonic()
        for st in model.manager._bans.values():
            assert st.banned_until - now <= cfg.ban_max * 1.25 + 0.01

        await session.__aexit__(None, None, None)
        faults.set_plan(None)
        for s in (s_a, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_probabilistic(tiny_model_dir):
    """Seeded probabilistic chaos (frame delays + rare resets) over several
    generations: tokens stay exact and the session always completes."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2)
        s_b = _server(model_dir, rc(), 2, 3)
        s_c = _server(model_dir, rc(), 2, 3)
        for s in (s_a, s_b, s_c):
            await s.start()

        plan = FaultPlan(seed=1234)
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           prob=0.3, delay_s=0.01))
        plan.add(FaultRule(site="send", action="reset", method="sitem",
                           prob=0.03))
        faults.set_plan(plan)

        cfg = ClientConfig(use_push=False, ban_timeout=0.5, ban_max=2.0,
                           max_retries=6)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        rng = np.random.default_rng(0)
        for trial in range(3):
            input_ids = rng.integers(0, config.vocab_size, size=(1, 5))
            ref = _hf_greedy(hf_model, input_ids, 8)
            ids = await model.generate(input_ids, max_new_tokens=8)
            np.testing.assert_array_equal(ids, ref)

        faults.set_plan(None)
        for s in (s_a, s_b, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


# ------------------------------------------------------------ deadline aborts
@pytest.mark.chaos
def test_server_aborts_expired_deadline_work(tiny_model_dir):
    """A step whose client budget (meta deadline_s) expires while it waits
    behind a jammed compute queue is dropped without compute or reply, and
    the drop is visible in rpc_info's deadlines_expired counter. A later
    in-budget step on the same session still answers."""
    model_dir, _, config = tiny_model_dir

    # scaled virtual clock: the jam duration and the step's budget are
    # both virtual, so the expiry ordering is identical at 1/3 the wall
    # time (the pickup sleeps below stay real — they wait on the worker
    # thread, not on protocol time — and burn 0.3 virtual seconds each,
    # which the jam length must comfortably cover)
    from bloombee_tpu.utils import clock as vclock
    from bloombee_tpu.utils.clock import ScaledClock

    async def run():
        s = _server(model_dir, None, 0, 3)
        await s.start()
        conn = await connect("127.0.0.1", s.port)
        stream = await conn.open_stream(
            "rpc_inference",
            {"session_id": "dl-test", "batch_size": 1, "max_length": 8},
        )
        # jam the single compute worker: the next step sits in queue while
        # its budget burns (the stalled-client scenario, server side)
        jam = asyncio.create_task(
            s.compute.submit(PRIORITY_INFERENCE, vclock.sleep, 0.9)
        )
        await asyncio.sleep(0.1)  # the jam is now running on the worker
        hidden = np.zeros((1, 2, config.hidden_size), np.float32)
        await stream.send(
            {"step": 0, "commit": True, "reply": "tensor",
             "deadline_s": 0.2},
            [hidden],
        )
        await jam
        await asyncio.sleep(0.1)
        assert s.deadlines_expired == 1  # dropped in queue, not computed

        # same session, sane budget: served normally (the drop above did
        # not poison the stream)
        await stream.send(
            {"step": 1, "commit": True, "reply": "tensor",
             "deadline_s": 60.0},
            [hidden],
        )
        item = await asyncio.wait_for(stream.recv(), 60.0)
        assert item is not None
        meta, tensors = item
        assert meta.get("step") == 1 and len(tensors) == 1

        info, _ = await conn.call("rpc_info", {})
        assert info["deadlines_expired"] == 1

        await stream.close()
        await conn.close()
        await s.stop()

    prev = vclock.install(ScaledClock(scale=3.0))
    try:
        asyncio.run(run())
    finally:
        vclock.install(prev)


# ------------------------------------------------------------- graceful drain
@pytest.mark.chaos
def test_sigterm_drain_finishes_inflight_and_routes_around(tiny_model_dir):
    """SIGTERM (via the same asyncio signal-handler wiring run_server
    installs) drains a server: it announces DRAINING, new sessions route
    around it, the in-flight session finishes normally, and the drain
    completes well inside drain_timeout once the session closes."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2, throughput=10.0)
        s_b = _server(model_dir, rc(), 2, 3, throughput=10.0,
                      drain_timeout=10.0)  # preferred; will be SIGTERM'd
        s_c = _server(model_dir, rc(), 2, 3, throughput=1.0)
        for s in (s_a, s_b, s_c):
            await s.start()

        input_ids = np.arange(5)[None, :] % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            config=ClientConfig(use_push=False),
        )
        session = model.inference_session(16, 1)
        await session.__aenter__()
        assert s_b.port in {
            sp.span.server_info.port for sp in session._spans
        }
        ids = await model.generate(input_ids, max_new_tokens=3,
                                   session=session)

        loop = asyncio.get_running_loop()
        drained = asyncio.Event()

        def _on_term():
            t = asyncio.create_task(s_b.drain())
            t.add_done_callback(lambda _t: drained.set())

        loop.add_signal_handler(signal.SIGTERM, _on_term)
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            await asyncio.sleep(0.3)  # drain announced; session still open
            assert not drained.is_set()  # blocked on our in-flight session

            # registry view: s_b is DRAINING, not gone
            reg_view = rc()
            infos = await reg_view.get_module_infos("tiny", range(3))
            assert (
                infos[2].servers[s_b.server_id].state
                == ServerState.DRAINING
            )
            await reg_view.close()

            # NEW sessions route around the draining server...
            model2 = DistributedModelForCausalLM.from_pretrained(
                model_dir, rc(), model_uid="tiny",
                config=ClientConfig(use_push=False),
            )
            session2 = model2.inference_session(16, 1)
            await session2.__aenter__()
            ports2 = {sp.span.server_info.port for sp in session2._spans}
            assert s_b.port not in ports2 and s_c.port in ports2
            await session2.__aexit__(None, None, None)

            # ...and a direct open against the draining server is refused
            # before any KV is allocated (a client racing a stale swarm
            # view must fail fast, not die mid-session)
            conn = await connect("127.0.0.1", s_b.port)
            st = await conn.open_stream(
                "rpc_inference",
                {"session_id": "late", "batch_size": 1, "max_length": 8},
            )
            try:
                item = await asyncio.wait_for(st.recv(), 5.0)
            except Exception:
                item = None
            assert item is None  # error or half-close — never a served item
            await conn.close()

            # ...while the in-flight session keeps stepping on s_b
            more = await model.generate(ids[:, -1:], max_new_tokens=3,
                                        session=session)
            final = np.concatenate([ids, more[:, 1:]], axis=1)
            np.testing.assert_array_equal(final, ref)
            await session.__aexit__(None, None, None)

            # with the last session closed, the drain wraps up quickly
            t0 = time.monotonic()
            await asyncio.wait_for(drained.wait(), 5.0)
            assert time.monotonic() - t0 < 5.0
        finally:
            loop.remove_signal_handler(signal.SIGTERM)

        for s in (s_a, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())
