"""Page-aligned content hash chains for cross-session prefix sharing.

The chain is the identity of a cached KV page: page i's hash covers its own
token ids AND the parent page's hash, so equal hashes imply equal *full
prefixes*, not just equal page contents (SGLang's RadixAttention collapses
the same property into a trie; a chained flat list is equivalent for the
page-granular pool in kv/paged.py and is trivially wire-serializable).

Shared by the client (hash computation over the prompt), the server
(pool lookup + adoption), the bench, and the tests — one definition so a
version skew shows up as a clean cache miss, never a wrong hit.
"""

from __future__ import annotations

import hashlib

import numpy as np

# bumped whenever the hash layout changes: a stale client's chains must
# miss, not alias, a newer server's pool
_CHAIN_VERSION = b"bbtpu-prefix-v1"
# hidden-state sessions (no token ids) hash raw activations instead; a
# distinct root guarantees a hidden chain can never alias an id chain
_HIDDEN_VERSION = b"bbtpu-hidden-v1"
# span-output digests (integrity layer): one-shot, not chained — each step's
# output stands alone so a single corrupted reply can't invalidate the rest
_DIGEST_VERSION = b"bbtpu-outdigest-v1"


def out_digest(arr) -> str:
    """blake2b hex digest over a span output's exact bytes.

    Canonicalizes only layout (C-contiguous), never dtype: the digest
    covers the bytes the server actually serialized, so the client can
    recompute it over the received array and detect *in-flight* corruption
    exactly. It is NOT a cross-replica equality check — honest replicas
    differ in ulps (batch-width-dependent float reductions), so two
    replicas' digests matching is a fast-path only; a mismatch must
    escalate to a tolerance compare, never straight to a verdict."""
    a = np.ascontiguousarray(arr)
    h = hashlib.blake2b(_DIGEST_VERSION, digest_size=16)
    h.update(str(a.dtype).encode("ascii"))
    h.update(str(a.shape).encode("ascii"))
    h.update(a.tobytes())
    return h.hexdigest()


def _extend_chain(
    pages_bytes, total_pages: int, chain: list[str] | None, root: bytes
) -> list[str]:
    """Shared chaining core: extend `chain` (treated as already covering
    its own length in pages) out to `total_pages` using `pages_bytes(p)`
    for page p's canonical byte content."""
    out = list(chain or [])
    if len(out) >= total_pages:
        return out[:total_pages]
    parent = out[-1].encode("ascii") if out else root
    for p in range(len(out), total_pages):
        digest = hashlib.blake2b(
            parent + pages_bytes(p), digest_size=16
        ).hexdigest()
        out.append(digest)
        parent = digest.encode("ascii")
    return out


def page_hash_chain(
    ids, page_size: int, chain: list[str] | None = None
) -> list[str]:
    """Chained hashes of the *full* pages of one row of token ids.

    Returns one hex digest per complete page (a trailing partial page gets
    no hash — it cannot be shared, its content is still growing). Token ids
    are canonicalized to int64 so the same prompt hashes identically
    whatever integer dtype the caller tokenized into. `chain` (an earlier
    result over a prefix of the same row) lets long-running sessions extend
    incrementally instead of rehashing from the root.
    """
    if page_size <= 0:
        raise ValueError(f"page_size must be positive, got {page_size}")
    row = np.asarray(ids).reshape(-1).astype(np.int64)
    return _extend_chain(
        lambda p: row[p * page_size : (p + 1) * page_size].tobytes(),
        len(row) // page_size, chain, _CHAIN_VERSION,
    )


def hidden_hash_chain(
    hidden, page_size: int, chain: list[str] | None = None
) -> list[str]:
    """Chained hashes of the full pages of one row of hidden states.

    `hidden` is [T, D] activations; bytes are canonicalized to contiguous
    float32 so the chain is stable across the dtypes a client may hold its
    history in. Used by hidden-state sessions (no token-id history) for
    recovery probes and replication — same pool, different hash root."""
    if page_size <= 0:
        raise ValueError(f"page_size must be positive, got {page_size}")
    row = np.ascontiguousarray(np.asarray(hidden), dtype=np.float32)
    if row.ndim != 2:
        raise ValueError(f"hidden row must be [T, D], got {row.shape}")
    return _extend_chain(
        lambda p: row[p * page_size : (p + 1) * page_size].tobytes(),
        row.shape[0] // page_size, chain, _HIDDEN_VERSION,
    )
