"""CacheManager admission control + host tiering.

Ports the intent of /root/reference/tests/test_cache.py (token budget,
blocking allocation, timeout) onto the asyncio single-process design.
"""

import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import AllocationTimeout, CacheManager


def make_manager(**kw):
    defaults = dict(
        num_layers=2, num_pages=8, page_size=4, n_kv_heads=1, head_dim=4,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return CacheManager(**defaults)


def test_allocation_budget_and_release():
    async def run():
        m = make_manager()  # capacity 32 tokens
        async with m.allocate(batch_size=2, max_length=8) as h:
            assert m.tokens_left == 16
            assert h.batch_size == 2
            async with m.allocate(1, 16):
                assert m.tokens_left == 0
        assert m.tokens_left == 32
        assert m.table.free_pages == 8  # seqs dropped, pages freed

    asyncio.run(run())


def test_oversized_request_rejected():
    async def run():
        m = make_manager()
        with pytest.raises(AllocationTimeout):
            async with m.allocate(1, 33):
                pass

    asyncio.run(run())


def test_allocation_blocks_until_free():
    async def run():
        m = make_manager()
        order = []

        async def first():
            async with m.allocate(1, 32):
                order.append("first-in")
                await asyncio.sleep(0.05)
            order.append("first-out")

        async def second():
            await asyncio.sleep(0.01)
            async with m.allocate(1, 8):
                order.append("second-in")

        await asyncio.gather(first(), second())
        assert order == ["first-in", "first-out", "second-in"]

    asyncio.run(run())


def test_allocation_timeout():
    async def run():
        m = make_manager()
        async with m.allocate(1, 32):
            with pytest.raises(AllocationTimeout):
                async with m.allocate(1, 8, timeout=0.05):
                    pass

    asyncio.run(run())


def test_park_unpark_roundtrip():
    async def run():
        m = make_manager()
        rng = np.random.default_rng(0)
        async with m.allocate(1, 16) as h:
            sid = h.seq_ids[0]
            k_new = rng.normal(size=(6, 1, 4)).astype(np.float32)
            v_new = rng.normal(size=(6, 1, 4)).astype(np.float32)
            slots = jnp.asarray(m.write_slots(h, 6))
            for layer in range(m.num_layers):
                m.arena["k"] = (
                    m.arena["k"].at[layer, slots].set(jnp.asarray(k_new))
                )
                m.arena["v"] = (
                    m.arena["v"].at[layer, slots].set(jnp.asarray(v_new))
                )
            pages_before = m.table.free_pages
            m.park_sequence(sid)
            assert m.table.free_pages == pages_before + 2  # device pages freed
            m.unpark_sequence(sid)
            assert m.table.seq(sid).l_acc == 6
            got = np.asarray(
                m.arena["k"][0][jnp.asarray(m.table.prefix_slots(sid))]
            )
            np.testing.assert_array_equal(got, k_new)

    asyncio.run(run())


def test_park_to_disk_roundtrip(tmp_path, monkeypatch):
    """Disk tier (reference TorchDisk): parked KV lives in a memmap, device
    pages free, unpark restores exactly."""
    import jax.numpy as jnp

    from bloombee_tpu.kv import arena as arena_ops

    monkeypatch.setenv("BBTPU_DISK_DIR", str(tmp_path))

    async def run():
        m = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=8, dtype=jnp.float32,
        )
        rng = np.random.default_rng(0)
        async with m.allocate(1, 12) as handle:
            slots = m.write_slots(handle, 6)
            k_new = rng.normal(size=(6, 2, 8)).astype(np.float32)
            v_new = rng.normal(size=(6, 2, 8)).astype(np.float32)
            ak, av = arena_ops.arena_write(
                m.arena["k"][0], m.arena["v"][0],
                jnp.asarray(slots), jnp.asarray(k_new), jnp.asarray(v_new),
            )
            m.arena["k"] = m.arena["k"].at[0].set(ak)
            m.arena["v"] = m.arena["v"].at[0].set(av)
            sid = handle.seq_ids[0]
            before = np.asarray(m.arena["k"][0, slots])
            free_before = m.table.free_pages
            m.park_sequence(sid, tier="disk")
            assert m.table.free_pages > free_before  # pages actually freed
            parked_k = m._parked[sid][0]
            assert isinstance(parked_k, np.memmap)
            m.unpark_sequence(sid)
            after = np.asarray(m.arena["k"][0, m.table.prefix_slots(sid)])
            np.testing.assert_array_equal(after, before)

    import asyncio

    asyncio.run(run())
