"""Span computation from per-block server records.

Port of /root/reference/src/bloombee/utils/dht.py:119-153 `compute_spans`:
collapse {block_idx -> {server_id -> info}} into each server's contiguous
span. A server announcing disjoint ranges yields its longest contiguous run
(the reference assumes contiguity by construction).
"""

from __future__ import annotations

from bloombee_tpu.swarm.data import ModuleInfo, RemoteSpanInfo, ServerState



def compute_spans(
    module_infos: list[ModuleInfo],
    min_state: ServerState = ServerState.ONLINE,
    include_draining: bool = True,
) -> dict[str, RemoteSpanInfo]:
    """server_id -> RemoteSpanInfo covering its contiguous live blocks.

    DRAINING servers are included by default (their open sessions must keep
    resolving them); pass include_draining=False for views that pick targets
    for NEW work (routing handles this via _active_spans; block selection
    should not count a departing server as coverage)."""
    spans: dict[str, RemoteSpanInfo] = {}
    for block_idx, info in enumerate(module_infos):
        if info is None:
            continue
        for peer_id, server in info.servers.items():
            if server.state < min_state:
                continue
            if not include_draining and server.state == ServerState.DRAINING:
                continue
            span = spans.get(peer_id)
            if span is None:
                spans[peer_id] = RemoteSpanInfo(
                    peer_id, block_idx, block_idx + 1, server
                )
            elif span.end == block_idx:
                span.end = block_idx + 1
            # non-contiguous announcement: keep the first run
    return spans
