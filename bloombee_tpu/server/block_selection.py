"""Swarm load balancing: which blocks should a new server host?

Port of /root/reference/src/bloombee/server/block_selection.py:12-95:
build the per-block aggregate-throughput vector from announced spans, pick
the contiguous window with minimum total throughput (the least-served
region), and decide whether an existing server should move
(`should_choose_other_blocks` with the balance_quality=0.75 hysteresis so
servers don't thrash).
"""

from __future__ import annotations

from collections import deque

import numpy as np

from bloombee_tpu.swarm.data import ModuleInfo, RemoteSpanInfo
from bloombee_tpu.swarm.load import predicted_queue_delay_s
from bloombee_tpu.utils import clock, env

BALANCE_QUALITY = 0.75

env.declare(
    "BBTPU_MEASURED_REBALANCE", bool, True,
    "weight the rebalance objective by live load adverts: a server's "
    "contribution to per-block throughput is discounted by its predicted "
    "queue delay (staleness-discounted, hostile-advert-bounded — same "
    "term the client router uses), so chronically hot spans attract "
    "movers and idle spans shed them. Servers without a load advert keep "
    "their static throughput, so a swarm with no adverts reduces to the "
    "static Petals objective (cold-start fallback). Off = static "
    "objective always",
)


def _effective_throughput(server, now: float | None) -> float:
    """A server's load-discounted contribution to block throughput: the
    static announced rate divided by (1 + predicted queue delay). The
    delay term is the shared swarm/load.py reading of the advert —
    bounded by LOAD_DELAY_CAP_S, so a hostile advert can shrink only its
    OWN server's weight and only ~11x; absent/stale adverts contribute 0
    delay, leaving the static throughput untouched."""
    t = server.throughput or 0.0
    return t / (1.0 + predicted_queue_delay_s(server, now))


def block_throughputs(
    module_infos: list[ModuleInfo],
    measured: bool = False,
    now: float | None = None,
) -> np.ndarray:
    """Aggregate announced throughput per block. With measured=True each
    server's contribution is discounted by its live load advert (see
    _effective_throughput); with no adverts in the swarm the result is
    identical to the static aggregate."""
    if measured and now is None:
        now = clock.now()
    out = np.zeros(len(module_infos))
    for i, info in enumerate(module_infos):
        for server in info.servers.values():
            if measured:
                out[i] += _effective_throughput(server, now)
            else:
                out[i] += server.throughput or 0.0
    return out


def choose_best_blocks(
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
    num_blocks: int,
) -> tuple[int, int]:
    """Least-served contiguous window of `num_blocks`."""
    tput = block_throughputs(module_infos)
    num_blocks = min(num_blocks, len(tput))
    best_start, best_sum = 0, float("inf")
    for start in range(len(tput) - num_blocks + 1):
        s = float(tput[start : start + num_blocks].sum())
        if s < best_sum:
            best_start, best_sum = start, s
    return best_start, best_start + num_blocks


def _best_landing(
    without: np.ndarray, n: int, t: float
) -> tuple[float | None, int | None]:
    """Best window of length `n` to add throughput `t` onto `without`:
    returns (resulting bottleneck min, window start), maximizing the min.
    O(blocks) — equivalent to copying the array per candidate start and
    taking its min (the naive O(blocks^2) form this replaced; equivalence
    is property-tested in tests/test_rebalance.py), because the candidate
    min decomposes into min(prefix-min before the window, window-min + t,
    suffix-min after), with window minima from one monotonic-deque sweep.
    Ties keep the earliest start, matching the naive scan order."""
    b = len(without)
    if n <= 0 or n > b:
        return None, None
    inf = float("inf")
    prefix = np.empty(b + 1)  # prefix[i] = min(without[:i])
    prefix[0] = inf
    np.minimum.accumulate(without, out=prefix[1:])
    suffix = np.empty(b + 1)  # suffix[i] = min(without[i:])
    suffix[b] = inf
    suffix[:b] = np.minimum.accumulate(without[::-1])[::-1]
    best, best_start = None, None
    dq: deque[int] = deque()  # indices of increasing window candidates
    for i in range(b):
        while dq and without[dq[-1]] >= without[i]:
            dq.pop()
        dq.append(i)
        start = i - n + 1
        if dq[0] < start:
            dq.popleft()
        if start >= 0:
            m = min(
                float(prefix[start]),
                float(without[dq[0]]) + t,
                float(suffix[start + n]),
            )
            if best is None or m > best:
                best, best_start = m, start
    return best, best_start


def _rebalance_decision(
    peer_id: str,
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
    measured: bool | None = None,
    now: float | None = None,
) -> tuple[tuple[int, int] | None, bool]:
    """(target, skipped_by_hysteresis): the move decision plus whether a
    strictly-better landing existed but fell inside the BALANCE_QUALITY
    margin (surfaced as the rebalance_skipped_hysteresis counter)."""
    my_span = spans.get(peer_id)
    if my_span is None:
        return None, False
    if measured is None:
        measured = bool(env.get("BBTPU_MEASURED_REBALANCE"))
    if now is None:
        now = clock.now()
    tput = block_throughputs(module_infos, measured=measured, now=now)
    current_min = float(tput.min())

    # simulate leaving: subtract the same contribution block_throughputs
    # added for me (load-discounted in measured mode)
    mine = (
        _effective_throughput(my_span.server_info, now)
        if measured
        else (my_span.server_info.throughput or 0.0)
    )
    without = tput.copy()
    without[my_span.start : my_span.end] -= mine
    # best place to re-land. The mover lands with its STATIC throughput
    # even in measured mode: moving drains its queue, so its current
    # congestion should not follow it to the new span (that asymmetry is
    # what makes hot spans attract movers and lets a hot mover escape).
    n = my_span.length
    best, best_start = _best_landing(
        without, n, my_span.server_info.throughput or 0.0
    )
    if best is None or (best_start, best_start + n) == (
        my_span.start, my_span.end
    ):
        # in measured mode "re-land where I am, minus my queue" can look
        # like an improvement; staying put is never a move
        return None, False
    if best * BALANCE_QUALITY > current_min:
        return (best_start, best_start + n), False
    # a strictly better landing exists but not by enough to beat the
    # thrash-guard margin
    return None, best > current_min


def rebalance_target(
    peer_id: str,
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
    measured: bool | None = None,
    now: float | None = None,
) -> tuple[int, int] | None:
    """The (start, end) this server should move its span to, or None when
    staying put is within the hysteresis margin. Simulates leaving and
    re-landing at every window, keeping the one that maximizes the swarm's
    bottleneck (minimum per-block) throughput; a move only wins if it
    beats the current bottleneck by more than BALANCE_QUALITY (reference
    should_choose_other_blocks, block_selection.py:40-95). With
    measured=True (default via BBTPU_MEASURED_REBALANCE) per-server
    throughput is discounted by live load adverts; a swarm with no
    adverts degrades to the static objective."""
    target, _ = _rebalance_decision(
        peer_id, module_infos, spans, measured=measured, now=now
    )
    return target


def should_choose_other_blocks(
    peer_id: str,
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
) -> bool:
    """Would moving this server's span improve the swarm's bottleneck
    throughput by more than the hysteresis margin?"""
    if spans.get(peer_id) is None:
        return True
    return rebalance_target(peer_id, module_infos, spans) is not None


def estimate_block_bytes(spec, dtype) -> int:
    """Parameter bytes of one block (reference block_utils.get_block_size:
    param count x dtype width, meta-device instantiation not needed — the
    spec already knows the shapes)."""
    import numpy as np

    d, i = spec.hidden_size, spec.intermediate_size
    h, kv, hd = (
        spec.num_attention_heads, spec.num_key_value_heads, spec.head_dim,
    )
    attn = d * h * hd + 2 * d * kv * hd + h * hd * d
    if spec.num_experts:
        mlp = spec.num_experts * 3 * d * i + d * spec.num_experts
    elif spec.mlp_type == "silu" or spec.mlp_type == "gelu_tanh_gated":
        mlp = 3 * d * i
    else:
        mlp = 2 * d * i
    norms = 4 * d
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 2
    return (attn + mlp + norms) * itemsize


def choose_num_blocks(
    spec, dtype, num_pages: int, page_size: int, memory_fraction: float = 0.8
) -> int:
    """How many blocks fit in this device's memory, after the KV arena
    (reference Server._choose_num_blocks, server.py:427-477). Falls back to
    the whole model when the backend exposes no memory stats (e.g. CPU)."""
    import numpy as np

    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        limit = stats["bytes_limit"]
    except Exception:
        return spec.num_hidden_layers
    per_block = estimate_block_bytes(spec, dtype)
    arena_bytes = (
        num_pages * page_size * spec.num_key_value_heads * spec.head_dim
        * 2 * np.dtype(dtype).itemsize
    )  # per layer (k+v)
    budget = limit * memory_fraction
    n = int(budget // (per_block + arena_bytes))
    return max(1, min(n, spec.num_hidden_layers))


def _bump(server, counter: str) -> None:
    """Increment an optional counter attribute (fake/minimal servers in
    tests don't carry the counter surface; skip them silently)."""
    try:
        setattr(server, counter, getattr(server, counter, 0) + 1)
    except (AttributeError, TypeError):
        pass


async def rebalance_if_needed(server) -> bool:
    """Periodic check driven by the server's supervisor loop: fetch swarm
    state, decide, and MOVE (drain, reload the new span, re-announce) via
    server.rebalance_to. Returns True when a move happened (reference
    server.py:479-542 _should_choose_other_blocks + restart loop). Every
    decision feeds a counter (rebalances_moved / rebalances_failed /
    rebalance_skipped_hysteresis) surfaced via rpc_info + health --probe."""
    from bloombee_tpu.swarm.spans import compute_spans

    infos = await server.registry.get_module_infos(
        server.model_uid, range(server.spec.num_hidden_layers)
    )
    # a DRAINING server is leaving: its span is not real coverage, so the
    # balance decision must see the post-departure swarm
    target, skipped = _rebalance_decision(
        server.server_id, infos, compute_spans(infos, include_draining=False)
    )
    if skipped:
        _bump(server, "rebalance_skipped_hysteresis")
    if target is None or target == (server.start_block, server.end_block):
        return False
    try:
        await server.rebalance_to(*target)
    except Exception:
        # rebalance_to's own failure path re-announces the old span; the
        # supervisor tick logs and retries next period
        _bump(server, "rebalances_failed")
        raise
    _bump(server, "rebalances_moved")
    return True
