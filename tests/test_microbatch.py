"""Within-stage micro-batch pipelining (reference microbatch_config.py
overlap-only mode, handler.py:1850-2151 accumulate/immediate queues).

Correctness: a micro-batched session must produce exactly the tokens of a
whole-batch session. Overlap: with per-chunk compute delays injected, a
2-chunk pipeline over 2 servers must beat the whole-batch serial time
(stage N+1 computes chunk k while stage N computes chunk k+1).
"""

import asyncio
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_mb")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _server(model_dir, reg_port, start, end):
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=RegistryClient("127.0.0.1", reg_port),
        compute_dtype=jnp.float32, num_pages=64, page_size=4,
    )


@pytest.mark.parametrize("use_push", [True, False])
def test_microbatched_generate_matches_hf(tiny, use_push):
    model_dir, hf_model, config = tiny

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, reg.port, 0, 2)
        s2 = _server(model_dir, reg.port, 2, 3)
        await s1.start()
        await s2.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", use_push=use_push,
        )
        rng = np.random.default_rng(3)
        input_ids = rng.integers(0, config.vocab_size, size=(4, 6))
        session = model.inference_session(24, 4, microbatch=2)
        await session.__aenter__()
        ids = await model.generate(input_ids, max_new_tokens=8,
                                   session=session)
        await session.__aexit__(None, None, None)
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=8, do_sample=False,
                use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)
        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_microbatch_overlap_beats_serial(tiny):
    """Inject compute delay proportional to chunk rows; the 2-chunk pipeline
    across 2 servers must finish decode faster than whole-batch serial
    (total step time < sum of span compute times)."""
    from bloombee_tpu.utils import clock as vclock
    from bloombee_tpu.utils.clock import ScaledClock

    model_dir, _, config = tiny
    PER_ROW = 0.04
    B, STEPS = 4, 6

    def slow(server):
        orig = server.executor.decode

        def wrapper(handle, hidden, **kw):
            # injected per-row delay on the scaled clock: the 2x scale
            # halves the wall cost of both runs while leaving their
            # RATIO (what the assertion compares) untouched
            vclock.sleep(PER_ROW * hidden.shape[0])
            return orig(handle, hidden, **kw)

        server.executor.decode = wrapper

    async def run(mb):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, reg.port, 0, 2)
        s2 = _server(model_dir, reg.port, 2, 3)
        await s1.start()
        await s2.start()
        slow(s1)
        slow(s2)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", use_push=True,
        )
        rng = np.random.default_rng(0)
        input_ids = rng.integers(0, config.vocab_size, size=(B, 4))
        session = model.inference_session(32, B, microbatch=mb)
        await session.__aenter__()
        hidden = model.embed(input_ids)
        out = await session.step(hidden)  # prefill, not timed
        step_h = out[:, -1:]
        out = await session.step(step_h)  # warm the decode bucket, not timed
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = await session.step(step_h)
        elapsed = time.perf_counter() - t0
        await session.__aexit__(None, None, None)
        await s1.stop()
        await s2.stop()
        await reg.stop()
        return elapsed, np.asarray(out)

    prev = vclock.install(ScaledClock(scale=2.0))
    try:
        serial_t, serial_out = asyncio.run(run(1))
        pipe_t, pipe_out = asyncio.run(run(2))
    finally:
        vclock.install(prev)
    np.testing.assert_allclose(pipe_out, serial_out, atol=1e-5, rtol=1e-5)
    # serial: STEPS * 2 spans * B*PER_ROW = 6*2*0.16 = 1.92s of injected
    # (virtual) delay; pipelined ideal = 6 * 3 slots * 0.08 = 1.44s
    # (+ overhead) — a ~0.5s virtual (0.25s wall at 2x) margin so
    # scheduler noise can't flip the comparison
    assert pipe_t < serial_t * 0.92, (pipe_t, serial_t)


def test_auto_microbatch_sizes_to_pipeline_depth(tiny):
    """microbatch='auto' picks chunks = pipeline depth for multi-span
    batched steps and stays whole-batch for single-row or single-span
    sessions (reference microbatch_config derives the count from the
    deployment)."""
    model_dir, hf_model, config = tiny

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, reg.port, 0, 2)
        s2 = _server(model_dir, reg.port, 2, 3)
        await s1.start()
        await s2.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        input_ids = np.arange(12).reshape(4, 3) % config.vocab_size
        # spy on the first server's item handler to see the resolved chunking
        seen_mb = []
        orig = s1._handle_item

        async def spy(session, stream, meta, tensors):
            seen_mb.append(int(meta.get("mb_of", 1)))
            return await orig(session, stream, meta, tensors)

        s1._handle_item = spy
        # drive the session directly so we can inspect the resolved chunking
        async with model.inference_session(16, 4, microbatch="auto") as sess:
            assert len(sess._spans) == 2
            out = await sess.step(
                model.embed(input_ids), ids=input_ids
            )
        # auto resolved to chunks == pipeline depth (2 spans -> mb_of == 2)
        assert seen_mb and set(seen_mb) == {2}, seen_mb
        logits = model.logits(out)
        with torch.no_grad():
            ref = hf_model(torch.tensor(input_ids)).logits.numpy()
        np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=2e-3)

        # batch 1: auto degrades to whole-batch (no chunk overhead)
        seen_mb.clear()
        async with model.inference_session(16, 1, microbatch="auto") as sess:
            one = await sess.step(model.embed(input_ids[:1]))
        assert seen_mb and set(seen_mb) == {1}, seen_mb
        np.testing.assert_allclose(
            model.logits(one), ref[:1], atol=2e-3, rtol=2e-3
        )
        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())
