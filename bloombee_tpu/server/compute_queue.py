"""Prioritized single-worker compute queue.

Role of the reference's PrioritizedTaskPool + hivemind Runtime
(/root/reference/src/bloombee/server/task_pool.py:30-236, task_prioritizer.py):
all device work funnels through one worker so steps execute one at a time
(the TPU is a serial resource), inference outranks forward/backward, and the
asyncio event loop never blocks on device compute.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

PRIORITY_INFERENCE = 0.0  # reference DummyTaskPrioritizer: inference=1.0
PRIORITY_TRAINING = 1.0  # beats forward/backward=2.0 — same ordering


class DeadlineExpired(RuntimeError):
    """The task's client-supplied deadline passed while it sat in the
    queue: the client has already given up, so running it would only
    delay work somebody still wants."""


class ComputeQueue:
    def __init__(self) -> None:
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._seq = itertools.count()
        self._thread = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="compute"
        )
        self._worker_task: asyncio.Task | None = None

    def start(self) -> None:
        self._worker_task = asyncio.create_task(self._worker())

    async def stop(self) -> None:
        if self._worker_task is not None:
            self._worker_task.cancel()
        self._thread.shutdown(wait=False, cancel_futures=True)

    async def submit(
        self,
        priority: float,
        fn: Callable[..., Any],
        *args,
        deadline: float | None = None,  # time.monotonic() cutoff: the task
        # is abandoned (DeadlineExpired) if the worker reaches it later
        **kwargs,
    ) -> Any:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.put_nowait(
            (priority, next(self._seq), deadline, fn, args, kwargs, fut)
        )
        return await fut

    async def _worker(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            _, _, deadline, fn, args, kwargs, fut = await self._queue.get()
            if fut.cancelled():
                continue
            if deadline is not None and time.monotonic() > deadline:
                # checked at execution time, not submit time: a deep queue
                # behind a slow step is exactly when expiry happens
                if not fut.done():
                    fut.set_exception(
                        DeadlineExpired(
                            "deadline passed while queued; dropping compute"
                        )
                    )
                continue
            try:
                result = await loop.run_in_executor(
                    self._thread, lambda: fn(*args, **kwargs)
                )
                if not fut.done():
                    fut.set_result(result)
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
