"""Ban lifecycle unit tests: exponential backoff, half-open probe
re-admission, pruning, and exact missing-block reporting.

Pure routing-layer tests (registry=None, spans injected directly) — no
servers, no jax compute, so these run in milliseconds and pin down the
state machine the chaos e2e suite exercises end-to-end.
"""

import random
import time

import pytest

from bloombee_tpu.client.sequence_manager import (
    MissingBlocksError,
    RemoteSequenceManager,
)
from bloombee_tpu.swarm.data import RemoteSpanInfo, ServerInfo, ServerState
from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import SteppableClock


@pytest.fixture
def stepper():
    """Hand-stepped process clock: the manager's ban/probe state machine
    reads clock.monotonic(), so tests advance virtual time instead of
    sleeping — identical transitions, zero wall-clock waits."""
    c = SteppableClock()
    prev = clock.install(c)
    yield c
    clock.install(prev)


def _span(peer_id, start, end, **info_kw):
    info_kw.setdefault("host", "127.0.0.1")
    info_kw.setdefault("port", 7000 + hash(peer_id) % 100)
    return RemoteSpanInfo(
        peer_id, start, end, ServerInfo(start_block=start, end_block=end,
                                        **info_kw)
    )


def _manager(num_blocks=2, **kw):
    kw.setdefault("ban_timeout", 0.2)
    kw.setdefault("ban_max", 1.0)
    kw.setdefault("rng", random.Random(0))
    return RemoteSequenceManager(None, "uid", num_blocks, **kw)


def test_banned_peer_excluded_from_routes():
    m = _manager()
    m.spans = {"a": _span("a", 0, 2), "b": _span("b", 0, 2)}
    m.ban_peer("a")
    for _ in range(5):
        route = m.make_sequence()
        assert [s.peer_id for s in route] == ["b"]


def test_ban_backoff_doubles_with_jitter_and_caps():
    m = _manager(ban_timeout=1.0, ban_max=4.0)
    durations = []
    for _ in range(5):
        before = time.monotonic()
        m.ban_peer("a")
        durations.append(m._bans["a"].banned_until - before)
    # strikes 1..5 -> base backoff 1, 2, 4, 4, 4 (capped), each with
    # 0.75-1.25x jitter
    for got, base in zip(durations, [1.0, 2.0, 4.0, 4.0, 4.0]):
        assert base * 0.75 <= got <= base * 1.25 + 0.01
    # a success resets the whole history: the next failure starts over
    m.note_peer_ok("a")
    assert "a" not in m._bans
    m.ban_peer("a")
    assert m._bans["a"].strikes == 1


def test_half_open_probe_admits_one_route(stepper):
    m = _manager(ban_timeout=0.05, ban_max=0.05)
    m.spans = {"a": _span("a", 0, 2), "b": _span("b", 0, 2)}
    m.ban_peer("a")
    now = clock.monotonic()
    assert m._ban_excludes("a", now)  # still banned
    stepper.advance(0.08)
    now = clock.monotonic()
    # ban expired: the FIRST caller becomes the half-open trial...
    assert not m._ban_excludes("a", now)
    assert m._bans["a"].probing
    # ...and other routes keep avoiding the peer while the trial runs
    assert m._ban_excludes("a", now)
    # trial succeeds -> fully re-admitted everywhere
    m.note_peer_ok("a")
    assert "a" not in m._bans
    assert not m._ban_excludes("a", clock.monotonic())


def test_probe_lease_expires_so_peer_is_not_stuck(stepper):
    """If the trial route never resolves (client went away mid-probe), the
    probe lease expires and the next route re-probes instead of the peer
    being excluded forever."""
    m = _manager(ban_timeout=0.01, ban_max=0.01)
    m.ban_peer("a")
    stepper.advance(0.02)
    assert not m._ban_excludes("a", clock.monotonic())  # trial 1
    st = m._bans["a"]
    assert st.probing and st.probe_until > clock.monotonic()
    st.probe_until = clock.monotonic() - 1.0  # the trial went silent
    assert not m._ban_excludes("a", clock.monotonic())  # trial renewed
    assert st.probe_until > clock.monotonic()


def test_probe_failure_rebans_with_next_doubling(stepper):
    m = _manager(ban_timeout=0.05, ban_max=10.0)
    m.ban_peer("a")
    stepper.advance(0.08)
    assert not m._ban_excludes("a", clock.monotonic())  # half-open trial
    m.ban_peer("a")  # the trial failed
    st = m._bans["a"]
    assert st.strikes == 2 and not st.probing
    remaining = st.banned_until - clock.monotonic()
    assert 0.05 * 2 * 0.74 <= remaining <= 0.05 * 2 * 1.25 + 0.01


def test_missing_blocks_error_reports_exact_indices():
    m = _manager(num_blocks=5)
    m.spans = {"a": _span("a", 0, 2), "b": _span("b", 3, 4)}
    with pytest.raises(MissingBlocksError) as ei:
        m.make_sequence()
    assert ei.value.blocks == [2, 4]


def test_prune_bans_drops_departed_and_long_expired():
    m = _manager(ban_timeout=0.01, ban_max=0.01)
    m.spans = {"b": _span("b", 0, 2)}
    m.ban_peer("a")  # not in spans anymore -> departed
    m.ban_peer("b")
    m._prune_bans()
    assert "a" not in m._bans and "b" in m._bans
    # long-expired (> banned_until + 4*ban_max) entries age out too
    m._bans["b"].banned_until = time.monotonic() - 1.0
    m._prune_bans()
    assert "b" not in m._bans


def test_ban_forgets_measured_rtt():
    """Banning drops the peer's RTT EMA: a recovered server re-measures
    instead of routing on its pre-failure latency."""
    m = _manager()
    m.pinger.record("a", 0.002)
    assert m.pinger.get("a", 9.9) == pytest.approx(0.002)
    m.ban_peer("a")
    assert m.pinger.get("a", 9.9) == 9.9
    assert m.pinger.needs_measure("a")


def test_draining_servers_excluded_from_new_routes():
    m = _manager()
    m.spans = {
        "a": _span("a", 0, 2, state=ServerState.DRAINING, throughput=10.0),
        "b": _span("b", 0, 2, throughput=1.0),
    }
    for _ in range(5):
        assert [s.peer_id for s in m.make_sequence()] == ["b"]
