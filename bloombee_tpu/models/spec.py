"""ModelSpec: the static shape/hyperparameter description of a model family.

This is the hashable static argument threaded through every jitted function —
the TPU-native replacement for the reference's Distributed*Config carrying an HF
config object around (/root/reference/src/bloombee/models/llama/config.py:16-19).
Keeping it a frozen dataclass of primitives means it can be a `jax.jit` static
arg and a compilation-cache key.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    family: str
    hidden_size: int
    intermediate_size: int
    num_attention_heads: int
    num_key_value_heads: int
    head_dim: int
    num_hidden_layers: int
    vocab_size: int
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 4096
    # MoE (Mixtral-style); 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 0
    # router semantics: Mixtral masks then softmaxes over the top-k;
    # Qwen3-MoE softmaxes over ALL experts first, selects top-k, and
    # optionally renormalizes (norm_topk_prob)
    moe_pre_softmax: bool = False
    moe_norm_topk: bool = False
    # Qwen3-style per-head q/k RMSNorm
    qk_norm: bool = False
    # Gemma-style sliding-window layers: pattern of layer types, e.g.
    # ("sliding", "sliding", "full", ...); empty = all full attention.
    layer_types: tuple[str, ...] = ()
    sliding_window: int = 0
    # Falcon/Bloom-style extras
    alibi: bool = False
    parallel_attn: bool = False
    num_ln_in_parallel_attn: int = 0
    attention_multiplier: float | None = None
    # Gemma-style logit soft-capping / embedding scaling
    logits_soft_cap: float = 0.0
    embedding_multiplier: float = 1.0
    # Per-layer rope theta override for sliding layers (Gemma3-style)
    rope_local_theta: float = 0.0
    # block structure knobs
    norm_type: str = "rms"  # "rms" | "ln"
    mlp_type: str = "silu"  # "silu" | "gelu" | "gelu_tanh_gated"
    sandwich_norms: bool = False  # Gemma2-style post-attn/post-ffn norms
    attn_logit_softcap: float = 0.0
    # Gemma-4-style heterogeneous attention geometry: full-attention layers
    # use their own head_dim / kv head count (reference backend.py:243-306
    # per-block-index KV descriptors) and may alias V to K
    global_head_dim: int = 0  # 0 = same as head_dim
    num_global_key_value_heads: int = 0  # 0 = same as num_key_value_heads
    k_eq_v_full: bool = False  # full layers share one K=V projection
    # this layer's resolved per-layer overrides (set by spec_for_layer)
    k_eq_v: bool = False

    def window_for_layer(self, layer_idx: int) -> int:
        return (
            self.sliding_window
            if self.layer_type(layer_idx) == "sliding"
            else 0
        )

    @property
    def gqa_groups(self) -> int:
        return self.num_attention_heads // self.num_key_value_heads

    def layer_type(self, layer_idx: int) -> str:
        if not self.layer_types:
            return "full"
        return self.layer_types[layer_idx % len(self.layer_types)]

    # ------------------------------------------------ per-layer geometry
    @property
    def heterogeneous(self) -> bool:
        """Layers differ in attention geometry (head_dim / kv heads)."""
        return bool(
            (self.global_head_dim and self.global_head_dim != self.head_dim)
            or (
                self.num_global_key_value_heads
                and self.num_global_key_value_heads
                != self.num_key_value_heads
            )
        )

    def head_dim_for_layer(self, layer_idx: int) -> int:
        if self.layer_type(layer_idx) == "full" and self.global_head_dim:
            return self.global_head_dim
        return self.head_dim

    def kv_heads_for_layer(self, layer_idx: int) -> int:
        if (
            self.layer_type(layer_idx) == "full"
            and self.num_global_key_value_heads
        ):
            return self.num_global_key_value_heads
        return self.num_key_value_heads

    def theta_for_layer(self, layer_idx: int) -> float:
        """Sliding layers may use a local rope base (Gemma3/4 style)."""
        if self.layer_type(layer_idx) == "sliding" and self.rope_local_theta:
            return self.rope_local_theta
        return self.rope_theta

    def spec_for_layer(self, layer_idx: int) -> "ModelSpec":
        """A uniform ModelSpec describing exactly this layer (static, so
        per-layer variants are jit cache keys like the base spec)."""
        full = self.layer_type(layer_idx) == "full"
        return dataclasses.replace(
            self,
            head_dim=self.head_dim_for_layer(layer_idx),
            num_key_value_heads=self.kv_heads_for_layer(layer_idx),
            rope_theta=self.theta_for_layer(layer_idx),
            k_eq_v=self.k_eq_v_full and full,
            global_head_dim=0,
            num_global_key_value_heads=0,
        )

    @classmethod
    def from_hf_config(cls, config: Any) -> "ModelSpec":
        """Build from a transformers PretrainedConfig (duck-typed)."""
        from bloombee_tpu.models.auto import spec_from_hf_config

        return spec_from_hf_config(config)
