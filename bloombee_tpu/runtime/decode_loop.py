"""Server-side multi-step greedy decode: N tokens per RPC, one jitted loop.

The TPU-first answer to the per-token host<->device round trip that floors
served single-session throughput (BASELINE.md timing decomposition: ~1 ms
dispatch + ~6 ms compute + ~95 ms round trip per decode step on a
tunnel-attached chip). When one server hosts the WHOLE model, the client can
hand it the last token id and let embed -> span -> norm+head -> select run
N times entirely on device (`lax.scan`), returning N token ids per RPC —
one round trip amortized over N tokens.

Reference analog to beat: `_fast_generate_greedy`
(/root/reference/src/bloombee/client/remote_generation.py:286-386), which
still round-trips hidden states once per token.

Exactness contract: on the same backend this loop is token-identical to the
client's per-step greedy path. The embed is computed in the table's dtype
then cast to the compute dtype (= the per-step path's fp32 host embed +
bf16 wire cast, which is exact for bf16/fp32 tables); the head consumes the
span output cast to fp32 (= the per-step path's wire fetch + np.float32
cast, exact because compute dtype == wire dtype); both use the SAME
embed/head math (models/head.py) and first-index argmax.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.models.head import embed_impl, norm_head_impl
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.runtime.step import span_step_impl


def decode_loop_impl(
    client_params: dict,  # embed table + final norm + lm_head
    span_params: dict,  # stacked per-layer span params (leading dim L)
    arena_k: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    arena_v: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    ids0: jax.Array,  # [B] int32: the input token of the FIRST step
    finished0: jax.Array,  # [B] bool: rows already at EOS (forced to eos_id)
    plans: jax.Array,  # [N, plan_len] packed int32, one per step
    lora: dict | None = None,  # per-request LoRA factors, leading dim L
    *,
    spec: ModelSpec,
    page_size: int,
    max_pages: int,
    eos_id: int = -1,  # -1: no EOS clamping
    compute_dtype=jnp.bfloat16,
    windows: tuple | None = None,
    use_paged: bool = False,
    attn_topk: int = 0,
):
    """Returns (tokens [B, N], arena_k, arena_v).

    tokens[:, i] is the token selected AFTER step i (greedy argmax over the
    fp32 logits), with EOS rows clamped to eos_id exactly like the client's
    per-step `finished` masking (client/model.py generate). Steps whose plan
    carries out-of-bounds slots (bucket padding beyond the requested count)
    produce garbage tokens the caller slices away; their KV writes are
    dropped by the scatter's drop mode.
    """
    has_embed_norm = "embed_norm" in client_params

    def body(carry, plan):
        ids, finished, ak, av = carry
        h = embed_impl(
            client_params,
            ids[:, None],
            spec.embedding_multiplier,
            has_embed_norm,
            spec.rms_norm_eps,
        ).astype(compute_dtype)
        h, ak, av = span_step_impl(
            span_params, ak, av, h, plan, None, lora=lora,
            spec=spec, page_size=page_size, max_pages=max_pages,
            windows=windows, use_paged=use_paged, attn_topk=attn_topk,
        )
        logits = norm_head_impl(
            client_params,
            h[:, 0].astype(jnp.float32),
            spec.rms_norm_eps,
            spec.logits_soft_cap,
            spec.norm_type,
        )  # [B, V] fp32
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_id >= 0:
            nxt = jnp.where(finished, eos_id, nxt)
            finished = finished | (nxt == eos_id)
        return (nxt, finished, ak, av), nxt

    (_, _, arena_k, arena_v), toks = lax.scan(
        body, (ids0, finished0, arena_k, arena_v), plans
    )
    return toks.T, arena_k, arena_v  # [B, N]


decode_loop = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "page_size", "max_pages", "eos_id", "compute_dtype",
        "windows", "use_paged", "attn_topk",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(decode_loop_impl)
