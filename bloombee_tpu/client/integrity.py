"""Client-side integrity layer: sanity gate, tolerance compare, audits.

Petals names the gap this closes: in a public swarm, peers may return
incorrect outputs — maliciously or via bad hardware — and the client feeds
whatever hidden states a server returns straight into the next span. This
module holds the pure pieces of the defense in depth:

- ``SanityGate``: a cheap O(B*D) inline check every received span output
  passes before entering the next span — all-finite plus a per-span running
  activation-RMS envelope. It catches the loud lies (NaN poison, large
  scaling) at the step they happen, BEFORE the token commits, so recovery
  replays from clean history and the final generation stays token-identical
  to a clean run.
- ``tensors_close``: the dtype-aware tolerance compare used by audits.
  NEVER exact equality: honest replicas differ in ulps because float
  reductions are batch-width dependent (a server batching our row with a
  stranger's sums in a different order). Exact compares convict honest
  peers; bbtpu-lint BB007 flags them.
- ``IntegrityError``: raised into the existing reroute+replay recovery
  path when a check fails — integrity rejects heal exactly like crashes.

Everything here is opt-in (``ClientConfig.integrity`` / ``BBTPU_INTEGRITY``,
``BBTPU_AUDIT_P``); off means byte-for-byte pre-integrity behavior.
"""

from __future__ import annotations

import logging

import ml_dtypes
import numpy as np

from bloombee_tpu.utils import env
from bloombee_tpu.wire.rpc import RpcError

logger = logging.getLogger(__name__)

env.declare(
    "BBTPU_INTEGRITY", bool, False,
    "enable the client integrity layer (inline sanity gate on every span "
    "output + out_digest verification) and server-side digest adverts",
)
env.declare(
    "BBTPU_AUDIT_P", float, 0.0,
    "per-step probability of re-executing a recorded span step on a "
    "different replica and tolerance-comparing the outputs (0 disables "
    "audits; implies the integrity layer for the session when > 0)",
)


class IntegrityError(RpcError):
    """A span output failed an integrity check. Subclasses RpcError so the
    session's existing retry loop heals it via reroute+replay — but the
    session skips the resume fast-path for it (resuming would retransmit
    to the same lying peer)."""


_BF16 = np.dtype(ml_dtypes.bfloat16)


def _as_f32(arr) -> np.ndarray:
    a = np.asarray(arr)
    if a.dtype != np.float32:
        a = a.astype(np.float32)
    return a


def rtol_for(dtype) -> float:
    """Audit comparison tolerance for a wire dtype (numpy dtype or wire
    name like "bf16"). Generous on purpose: the question is "is this peer
    lying", not "are these bit-identical" — honest cross-replica ulp
    drift must never convict."""
    if isinstance(dtype, str):
        from bloombee_tpu.wire.tensor_codec import dtype_for_name

        dt = dtype_for_name(dtype)
    else:
        dt = np.dtype(dtype)
    if dt in (_BF16, np.dtype(np.float16)):
        return 0.1
    if dt == np.dtype(np.float32):
        return 0.02
    return 1e-6


def tensors_close(a, b, dtype=None) -> bool:
    """Dtype-aware tolerance compare of two span outputs.

    ``dtype`` is the wire dtype the activations travelled in (defaults to
    the coarser of the two inputs' dtypes); the absolute floor scales with
    the reference RMS so near-zero channels don't demand absolute
    precision the format can't express."""
    aa, bb = np.asarray(a), np.asarray(b)
    if aa.shape != bb.shape:
        return False
    if dtype is None:
        dtype = max(
            (aa.dtype, bb.dtype),
            key=lambda d: rtol_for(d),
        )
    rtol = rtol_for(dtype)
    a32, b32 = _as_f32(aa), _as_f32(bb)
    rms = float(np.sqrt(np.mean(np.square(a32)))) if a32.size else 0.0
    atol = rtol * max(rms, 1e-6)
    return bool(np.allclose(a32, b32, rtol=rtol, atol=atol))


class SanityGate:
    """Per-span running activation-norm envelope plus all-finite check.

    Keyed by span block range (start, end) — not by peer — so a rerouted
    replacement server is judged against the same envelope its predecessor
    established. The envelope is high-side only with a generous margin:
    ulp-level drift between honest replicas is orders of magnitude below
    it, so a clean swarm never trips the gate (the false-positive suite
    pins this). Warmup observations are accepted unconditionally; stats
    update only on accepted outputs so one lie can't stretch the envelope
    for the next."""

    def __init__(self, margin: float = 4.0, warmup: int = 3):
        self.margin = float(margin)
        self.warmup = int(warmup)
        # span key -> [observations, max accepted per-position RMS]
        self._stats: dict[tuple, list] = {}

    def check(self, key, arr) -> str | None:
        """Returns None when `arr` passes, else a short reject reason."""
        a32 = _as_f32(arr)
        if not np.isfinite(a32).all():
            return "nonfinite"
        if a32.size == 0:
            return None
        # O(B*T*D): per-position RMS over the feature dim, worst position.
        # f64 accumulator: a x64-scaled bf16 lie squares past f32 range,
        # and an inf RMS accepted during warmup would poison the envelope
        rms = np.sqrt(np.mean(np.square(a32, dtype=np.float64), axis=-1))
        worst = float(rms.max())
        st = self._stats.get(key)
        if st is None:
            st = [0, 0.0]
            self._stats[key] = st
        if st[0] >= self.warmup and worst > self.margin * max(st[1], 1e-6):
            return (
                f"rms-envelope: {worst:.3g} > {self.margin:g}x"
                f" {st[1]:.3g}"
            )
        st[0] += 1
        st[1] = max(st[1], worst)
        return None
