"""Client-side drafter: builds token trees with a small local JAX model.

Role of the reference's MultiSSMDrafter (/root/reference/src/bloombee/models/
llama/spec_decoding_drafter.py:67-110, small HF models in threads). Here the
draft model is a dense JAX Llama run entirely client-side; tree shapes are
STATIC branching tuples (e.g. (4, 2, 1)) so every round reuses the same
compiled shapes — the reference's Sequoia-style dynamic shape optimization
(spec_decoding_tree_shape.py) maps to choosing the branching tuple offline.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.models.llama.block import block_forward, dense_attend
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import rms_norm
from bloombee_tpu.ops.rotary import rotary_cos_sin
from bloombee_tpu.spec.tree import DraftTree
from bloombee_tpu.spec.verify import _softmax
from bloombee_tpu.utils.tree import unstack_params


class LocalJaxDraftModel:
    """Small dense Llama run locally (no KV cache — recompute per level;
    draft models are tiny so this stays cheap and shape-stable)."""

    def __init__(self, spec: ModelSpec, block_params: list, client_params: dict):
        self.spec = spec
        self.blocks = block_params
        self.client = client_params

    @classmethod
    def from_dir(cls, model_dir: str, dtype=None) -> "LocalJaxDraftModel":
        from bloombee_tpu.models.checkpoint import (
            load_client_params,
            load_span_params,
            load_spec,
        )

        spec = load_spec(model_dir)
        stacked, _ = load_span_params(
            model_dir, 0, spec.num_hidden_layers, dtype=dtype
        )
        blocks = unstack_params(stacked, spec.num_hidden_layers)
        client = load_client_params(model_dir, dtype=dtype)
        return cls(spec, blocks, client)

    @functools.partial(jax.jit, static_argnums=0)
    def _last_logits(self, ids: jax.Array, last: jax.Array) -> jax.Array:
        """ids [N, S_bucket] right-padded; last [N] = true_len - 1."""
        spec = self.spec
        h = self.client["embed"][ids]
        b, s, _ = h.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)
        for p in self.blocks:
            h, _ = block_forward(p, spec, h, cos, sin, dense_attend())
        h_last = h[jnp.arange(b), last]  # causal mask: padding is invisible
        h_last = rms_norm(h_last, self.client["norm"], spec.rms_norm_eps)
        return (h_last @ self.client["lm_head"]).astype(jnp.float32)

    def last_logits(self, ids: np.ndarray) -> np.ndarray:
        """Bucket the context length (pow2) so round-over-round growth reuses
        compiled shapes instead of retracing every round."""
        return self.last_logits_ragged([list(row) for row in ids])

    def last_logits_ragged(self, seqs: list[list[int]]) -> np.ndarray:
        """Per-sequence next-token logits for ragged contexts (batched
        speculative rows have per-row lengths); right-padded to a pow2
        bucket, with the per-row `last` index selecting the true end (the
        causal mask keeps padding invisible)."""
        from bloombee_tpu.runtime.executor import next_pow2

        n = len(seqs)
        lens = [len(q) for q in seqs]
        sb = next_pow2(max(lens), floor=8)
        padded = np.zeros((n, sb), dtype=np.int64)
        for i, q in enumerate(seqs):
            padded[i, : len(q)] = q
        last = np.asarray([ln - 1 for ln in lens], dtype=np.int32)
        return np.asarray(
            self._last_logits(jnp.asarray(padded), jnp.asarray(last))
        )


class GreedyTreeDrafter:
    """Top-k tree expansion with static branching per depth."""

    def __init__(self, model: LocalJaxDraftModel, branching=(2, 2, 1)):
        self.model = model
        self.branching = tuple(branching)

    def build(self, context_ids: np.ndarray) -> tuple[DraftTree, np.ndarray]:
        """context_ids [S] -> (tree, draft_probs [T, V])."""
        trees, probs = self.build_batch([list(context_ids)])
        return trees[0], probs[0]

    def build_batch(
        self, contexts: list[list[int]]
    ) -> tuple[list[DraftTree], list[np.ndarray]]:
        """Per-row trees in ONE drafter call per depth (the reference drafts
        per-sample trees in parallel threads, speculative_model.py:33-117;
        here all rows' frontiers batch into one bucketed forward).

        All trees share the same static branching, hence identical parents/
        depths/mask structure — only tokens differ per row. draft_probs[r][i]
        is row r's drafter distribution at node i (for accept_sampling).
        """
        bsz = len(contexts)
        tokens = [[] for _ in range(bsz)]
        parents: list[int] = []  # shared across rows
        probs = [[] for _ in range(bsz)]
        # per-row frontier: list of (parent_index, path_ids)
        frontiers = [[(-1, list(c))] for c in contexts]
        for width in self.branching:
            n = len(frontiers[0])
            seqs = [f[1] for fr in frontiers for f in fr]  # [bsz*n] ragged
            logits = self.model.last_logits_ragged(seqs).reshape(
                bsz, n, -1
            )  # [bsz, n, V]
            p = _softmax(logits)
            top = np.argsort(-logits, axis=-1)[..., :width]  # [bsz, n, w]
            for r in range(bsz):
                new_frontier = []
                for fi, (parent, path) in enumerate(frontiers[r]):
                    for tok in top[r, fi]:
                        idx = len(tokens[r])
                        tokens[r].append(int(tok))
                        probs[r].append(p[r, fi])
                        new_frontier.append((idx, path + [int(tok)]))
                        if r == 0:
                            parents.append(parent)  # structure shared
                frontiers[r] = new_frontier
        par = np.asarray(parents, dtype=np.int32)
        trees = [
            DraftTree(tokens=np.asarray(tokens[r]), parents=par.copy())
            for r in range(bsz)
        ]
        return trees, [np.stack(pr) for pr in probs]
