"""Client-side drafter: builds token trees with a small local JAX model.

Role of the reference's MultiSSMDrafter (/root/reference/src/bloombee/models/
llama/spec_decoding_drafter.py:67-110, small HF models in threads). The
draft model is ANY registered dense family run client-side through the
family-generic dense block forward (runtime/layer_body.dense_block_forward
— the reference hardwires llama drafters; here llama/qwen2/qwen3/falcon
etc. all draft). Tree shapes are STATIC branching tuples (e.g. (4, 2, 1))
so every round reuses the same compiled shapes — the reference's
Sequoia-style dynamic shape optimization (spec_decoding_tree_shape.py)
maps to choosing the branching tuple offline.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.models.head import embed_impl, norm_head_impl
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops.attention import causal_mask, masked_attention
from bloombee_tpu.ops.rotary import rotary_cos_sin
from bloombee_tpu.runtime.layer_body import (
    attn_scale,
    dense_block_forward,
    dense_unsupported,
)
from bloombee_tpu.spec.tree import DraftTree
from bloombee_tpu.spec.verify import _softmax
from bloombee_tpu.utils.tree import unstack_params


class LocalJaxDraftModel:
    """Small dense model of any registered family run locally (KV caches
    managed here; draft models are tiny so shapes stay stable)."""

    def __init__(self, spec: ModelSpec, block_params: list, client_params: dict):
        reason = dense_unsupported(spec)
        if reason is not None:
            raise NotImplementedError(
                f"family {spec.family!r} cannot draft locally: {reason}"
            )
        self.spec = spec
        self.blocks = block_params
        self.client = client_params

    def _embed(self, ids: jax.Array) -> jax.Array:
        return embed_impl(
            self.client, ids, self.spec.embedding_multiplier,
            "embed_norm" in self.client, self.spec.rms_norm_eps,
        )

    def _head(self, h_last: jax.Array) -> jax.Array:
        return norm_head_impl(
            self.client, h_last, self.spec.rms_norm_eps,
            self.spec.logits_soft_cap, self.spec.norm_type,
        )

    @classmethod
    def from_dir(cls, model_dir: str, dtype=None) -> "LocalJaxDraftModel":
        from bloombee_tpu.models.checkpoint import (
            load_client_params,
            load_span_params,
            load_spec,
        )

        spec = load_spec(model_dir)
        stacked, _ = load_span_params(
            model_dir, 0, spec.num_hidden_layers, dtype=dtype
        )
        blocks = unstack_params(stacked, spec.num_hidden_layers)
        client = load_client_params(model_dir, dtype=dtype)
        return cls(spec, blocks, client)

    def _causal_attend(self, s: int):
        mask = causal_mask(s)[None]
        scale = attn_scale(self.spec)

        def attend(q, k, v):
            return masked_attention(q, k, v, mask, scale=scale), None

        return attend

    @functools.partial(jax.jit, static_argnums=0)
    def _last_logits(self, ids: jax.Array, last: jax.Array) -> jax.Array:
        """ids [N, S_bucket] right-padded; last [N] = true_len - 1."""
        spec = self.spec
        h = self._embed(ids)
        b, s, _ = h.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)
        attend = self._causal_attend(s)
        for p in self.blocks:
            h, _ = dense_block_forward(p, spec, h, cos, sin, attend)
        h_last = h[jnp.arange(b), last]  # causal mask: padding is invisible
        return self._head(h_last)

    # ------------------------------------------------- prefix-KV cached path
    @functools.partial(jax.jit, static_argnums=0)
    def _prefill_cache(self, ids: jax.Array, last: jax.Array):
        """One pass over the context: per-layer KV caches + last logits.
        Each tree level then reruns only its short path suffix against the
        cache instead of the whole context (the drafter half of the
        reference's threaded small-model drafting, drafter.py:67-110,
        which keeps HF KV caches the same way)."""
        spec = self.spec
        h = self._embed(ids)
        b, s, _ = h.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)
        attend = self._causal_attend(s)
        caches = []
        for p in self.blocks:
            h, (k, v) = dense_block_forward(p, spec, h, cos, sin, attend)
            caches.append((k, v))  # [N, Sb, Hkv, hd]
        h_last = h[jnp.arange(b), last]
        return tuple(caches), self._head(h_last)

    @functools.partial(jax.jit, static_argnums=0)
    def _suffix_logits(
        self,
        caches,  # per-layer (k, v) [N, Sb, Hkv, hd]
        ctx_lens: jax.Array,  # [N]
        row_of: jax.Array,  # [M] which cache row each path uses
        suffix_ids: jax.Array,  # [M, d] path tokens beyond the context
    ) -> jax.Array:
        """Logits after each path's last suffix token, attending to its
        row's cached prefix (masked to ctx_len) plus the suffix causally."""
        spec = self.spec
        m, d = suffix_ids.shape
        lens = ctx_lens[row_of]  # [M]
        h = self._embed(suffix_ids)
        positions = lens[:, None] + jnp.arange(d)[None, :]
        cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)

        sb = jax.tree.leaves(caches)[0].shape[1]
        col = jnp.arange(sb + d)[None, None, :]  # [1, 1, Sb+d]
        q_idx = jnp.arange(d)[None, :, None]  # [1, d, 1]
        prefix_ok = (col < sb) & (col < lens[:, None, None])
        suffix_ok = (col >= sb) & ((col - sb) <= q_idx)
        mask = prefix_ok | suffix_ok  # [M, d, Sb+d]
        scale = attn_scale(spec)

        def attend_for(pk, pv):
            def attend(q, k, v):
                k_all = jnp.concatenate([pk, k], axis=1)
                v_all = jnp.concatenate([pv, v], axis=1)
                return masked_attention(q, k_all, v_all, mask, scale=scale), None

            return attend

        for p, (k_c, v_c) in zip(self.blocks, caches):
            h, _ = dense_block_forward(
                p, spec, h, cos, sin,
                attend_for(k_c[row_of], v_c[row_of]),
            )
        h_last = h[:, -1]
        return self._head(h_last)

    def prefill_ragged(self, seqs: list[list[int]]):
        """(caches, ctx_lens, last_logits) for ragged contexts (pow2
        bucket)."""
        padded, lens = self._pad_ragged(seqs)
        caches, logits = self._prefill_cache(
            jnp.asarray(padded), jnp.asarray(lens - 1)
        )
        return caches, lens, np.asarray(logits)

    def last_logits(self, ids: np.ndarray) -> np.ndarray:
        """Bucket the context length (pow2) so round-over-round growth reuses
        compiled shapes instead of retracing every round."""
        return self.last_logits_ragged([list(row) for row in ids])

    @staticmethod
    def _pad_ragged(seqs: list[list[int]]):
        """Right-pad ragged sequences to a pow2 bucket (the shared shape
        discipline of the cached and uncached drafter paths)."""
        from bloombee_tpu.runtime.executor import next_pow2

        n = len(seqs)
        lens = np.asarray([len(q) for q in seqs], np.int32)
        sb = next_pow2(int(lens.max()), floor=8)
        padded = np.zeros((n, sb), dtype=np.int64)
        for i, q in enumerate(seqs):
            padded[i, : len(q)] = q
        return padded, lens

    def last_logits_ragged(self, seqs: list[list[int]]) -> np.ndarray:
        """Per-sequence next-token logits for ragged contexts (batched
        speculative rows have per-row lengths); right-padded to a pow2
        bucket, with the per-row `last` index selecting the true end (the
        causal mask keeps padding invisible)."""
        padded, lens = self._pad_ragged(seqs)
        return np.asarray(
            self._last_logits(jnp.asarray(padded), jnp.asarray(lens - 1))
        )


class GreedyTreeDrafter:
    """Top-k tree expansion with static branching per depth.

    `adaptive=True` retunes the branching tuple every few rounds from the
    observed per-depth acceptance histogram, under the initial tree's node
    budget (reference spec_decoding_tree_shape.py:116-250 Sequoia-style
    width optimization)."""

    def __init__(
        self, model: LocalJaxDraftModel, branching=(2, 2, 1),
        adaptive: bool = False, retune_every: int = 8,
        shape_cost_per_node: float = 0.05,
    ):
        from bloombee_tpu.spec.shape import AcceptanceStats, tree_nodes

        self.model = model
        self.branching = tuple(branching)
        self.adaptive = adaptive
        self.retune_every = retune_every
        self.shape_cost_per_node = float(shape_cost_per_node)
        self.stats = AcceptanceStats()
        self._budget_nodes = tree_nodes(self.branching)
        self._rounds = 0
        self.levels_drafted = 0
        self.levels_accepted = 0

    @property
    def accept_rate(self) -> float:
        """Measured drafted-level acceptance across every observed round —
        the client-side mirror of the server's spec_accept_rate counter."""
        return self.levels_accepted / max(self.levels_drafted, 1)

    def observe(self, accepted_lens: list[int]) -> None:
        """Feed per-row accepted DRAFTED-level counts from a verify round;
        periodically re-choose the branching when adaptive."""
        from bloombee_tpu.spec.shape import choose_branching

        depth = len(self.branching)
        for a in accepted_lens:
            self.stats.observe(int(a), self.branching)
            self.levels_drafted += depth
            self.levels_accepted += min(int(a), depth)
        self._rounds += 1
        if self.adaptive and self._rounds % self.retune_every == 0:
            self.branching = choose_branching(
                self.stats, budget_nodes=self._budget_nodes,
                cost_per_node=self.shape_cost_per_node,
                current=self.branching,
                grow_margin=2.0 * self.shape_cost_per_node,
            )

    def build(self, context_ids: np.ndarray) -> tuple[DraftTree, np.ndarray]:
        """context_ids [S] -> (tree, draft_probs [T, V])."""
        trees, probs = self.build_batch([list(context_ids)])
        return trees[0], probs[0]

    def build_batch(
        self, contexts: list[list[int]]
    ) -> tuple[list[DraftTree], list[np.ndarray]]:
        """Per-row trees in ONE drafter call per depth (the reference drafts
        per-sample trees in parallel threads, speculative_model.py:33-117;
        here all rows' frontiers batch into one bucketed forward).

        All trees share the same static branching, hence identical parents/
        depths/mask structure — only tokens differ per row. draft_probs[r][i]
        is row r's drafter distribution at node i (for accept_sampling).
        """
        bsz = len(contexts)
        tokens = [[] for _ in range(bsz)]
        parents: list[int] = []  # shared across rows
        probs = [[] for _ in range(bsz)]
        # one context pass builds per-layer KV caches; each level reruns
        # only its short path suffix against them
        caches, ctx_lens, logits0 = self.model.prefill_ragged(contexts)
        logits = logits0[:, None, :]  # [bsz, 1, V]: level-0 frontier
        # per-row frontier: list of (parent_index, suffix_token_list)
        frontiers = [[(-1, [])] for _ in range(bsz)]
        for level, width in enumerate(self.branching):
            p = _softmax(logits)
            top = np.argsort(-logits, axis=-1)[..., :width]  # [bsz, n, w]
            for r in range(bsz):
                new_frontier = []
                for fi, (parent, suffix) in enumerate(frontiers[r]):
                    for tok in top[r, fi]:
                        idx = len(tokens[r])
                        tokens[r].append(int(tok))
                        probs[r].append(p[r, fi])
                        new_frontier.append((idx, suffix + [int(tok)]))
                        if r == 0:
                            parents.append(parent)  # structure shared
                frontiers[r] = new_frontier
            if level + 1 < len(self.branching):
                n = len(frontiers[0])
                suffix_ids = np.asarray(
                    [f[1] for fr in frontiers for f in fr], np.int64
                )  # [bsz*n, level+1]
                row_of = np.repeat(np.arange(bsz), n)
                logits = np.asarray(
                    self.model._suffix_logits(
                        caches,
                        jnp.asarray(ctx_lens),
                        jnp.asarray(row_of),
                        jnp.asarray(suffix_ids),
                    )
                ).reshape(bsz, n, -1)
        par = np.asarray(parents, dtype=np.int32)
        trees = [
            DraftTree(tokens=np.asarray(tokens[r]), parents=par.copy())
            for r in range(bsz)
        ]
        return trees, [np.stack(pr) for pr in probs]
