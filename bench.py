"""Headline bench: Llama-3-8B-dimension SERVED span decode on one chip.

Two measurements on an 8-layer span with Llama-3-8B dimensions in bfloat16
(the per-chip unit of the north-star config — BASELINE.md: 8B from a v5e-8
swarm, 32 layers = 4 such spans):

1. **Served path (the headline)**: a real registry + BlockServer + client
   InferenceSession on loopback — every decode step pays wire serialization,
   the compute queue, one packed h2d, the jitted span step, and the d2h
   fetch, exactly like the reference's benchmark_inference.py measures
   (/root/reference/benchmarks/benchmark_inference.py:90-93).
2. **Fused-scan proxy (logged)**: 64 decode steps as ONE jitted lax.scan —
   the on-device ceiling with zero host involvement.

Prints exactly one JSON line for the served number:
  value = full-model-equivalent decode tokens/sec/sequence, i.e.
          served_span_steps_per_sec / 4 spans
  vs_baseline = value / 35.0  (A100 single-stream Llama-3-8B decode tok/s,
          the reference's north-star comparison point)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

from bloombee_tpu.utils import env


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Partial results stashed as each phase lands, so the watchdog can emit an
# honest JSON line even if the tunnel-attached backend wedges mid-phase (it
# did exactly that twice during round 2: any blocked transfer hangs forever
# inside PJRT with no Python-level way to interrupt it).
RESULTS: dict = {}
_DONE = threading.Event()
_EMITTED = threading.Lock()
_emitted = False


_compile_attr = {"phase": None, "compiles": 0, "ms": 0.0}


def _flush_compile_stats() -> None:
    """Attribute the XLA compiles observed since the last phase() call to
    the most recently named phase (phases run sequentially, so the window
    between two phase() calls belongs to the earlier one). Zeros when the
    jitwatch witness is off; a recompile storm shows up as a phase whose
    xla_compiles keeps growing across rounds."""
    from bloombee_tpu.utils import jitwatch

    c = jitwatch.counters()
    prev, now_n, now_ms = (
        _compile_attr["phase"], c["xla_compiles"], c["compile_ms_total"]
    )
    if prev is not None:
        stats = RESULTS.setdefault("compile_stats", {}).setdefault(
            prev, {"xla_compiles": 0, "compile_ms_total": 0.0}
        )
        stats["xla_compiles"] += now_n - _compile_attr["compiles"]
        stats["compile_ms_total"] = round(
            stats["compile_ms_total"] + now_ms - _compile_attr["ms"], 3
        )
    _compile_attr["compiles"] = now_n
    _compile_attr["ms"] = now_ms


def phase(name: str, status: str) -> None:
    """Phase ledger: every phase records started/ok/failed/skipped so a
    degraded run still shows WHICH phases are code-ready vs blocked (a
    bare rc=3 JSON is indistinguishable from missing phases — round-4
    verdict)."""
    _flush_compile_stats()
    _compile_attr["phase"] = name
    RESULTS.setdefault("phases", {})[name] = status
    log(f"[phase] {name}: {status}")


def emit_json():
    # exactly one JSON line, even if the watchdog fires while main is
    # finishing (both call emit_json around the same instant)
    global _emitted
    with _EMITTED:
        if _emitted:
            return
        _emitted = True
    _emit_json_locked()


def _emit_json_locked():
    served = RESULTS.get("served") or {}
    value = served.get("equiv_per_seq", 0.0)
    per_step = served.get("per_step_equiv_per_seq", 0.0)
    out = {
        "metric": "llama3_8b_equiv_served_decode_tok_per_s_per_seq",
        "value": round(value, 2),
        "unit": "tokens/sec/seq",
        # north-star ratio: USER-VISIBLE greedy serving tok/s (our best
        # served mode — decode_n when available) vs the A100 single-stream
        # HF decode baseline. vs_baseline_per_step is the mode-consistent
        # per-token-RPC ratio so the two serving modes stay distinguishable
        # (advisor, round 3).
        "vs_baseline": round(value / 35.0, 3),
        "vs_baseline_per_step": round(per_step / 35.0, 3),
        # per-step serving (one round trip per token) vs the headline,
        # which uses server-side multi-step decode when available
        "per_step_equiv_per_seq": round(per_step, 2),
        "server_decode_chunk": served.get("server_decode_chunk", 0),
        "effective_equiv_tok_per_s": round(
            served.get("effective_equiv_tok_per_s", 0.0), 1
        ),
        "fused_scan_proxy_tok_per_s_per_seq": round(
            RESULTS.get("proxy_equiv_per_seq", 0.0), 2
        ),
        "ttft_ms": round(served.get("ttft_ms", 0.0), 1),
        # the measured host<->device round-trip cost on this machine's
        # tunnel-attached chip: the floor under per-seq served latency
        # (production PCIe-attached v5e pays microseconds here)
        "host_device_round_trip_ms": round(RESULTS.get("fence_ms", 0.0), 1),
    }
    ctx = RESULTS.get("ctx4k")
    if ctx:
        out["ctx4k_paged_steps_per_s"] = round(ctx.get("paged", 0.0), 1)
        out["ctx4k_dense_steps_per_s"] = round(ctx.get("dense", 0.0), 1)
        out["ctx4k_paged_speedup"] = round(ctx.get("speedup", 0.0), 2)
        if "paged_int4" in ctx:
            out["ctx4k_paged_int4_steps_per_s"] = round(
                ctx["paged_int4"], 1
            )
        if "tree8_speedup" in ctx:
            out["ctx4k_tree8_verify_steps_per_s"] = round(
                ctx.get("tree8_paged", 0.0), 1
            )
            out["ctx4k_tree8_paged_speedup"] = round(
                ctx["tree8_speedup"], 2
            )
    chain = RESULTS.get("chain")
    if chain:
        out["server_decode_chain_steps_per_s"] = round(
            chain.get("steps_per_sec", 0.0), 1
        )
        out["server_decode_chain_chunk"] = chain.get("chunk", 0)
    pfx = RESULTS.get("prefix_cache")
    if pfx:
        # cross-session shared-prefix KV cache: cold vs warm TTFT for
        # sessions sharing a multi-page system prompt (warm sessions ship
        # only the uncached suffix) + the servers' hit accounting
        out["ttft_warm_ms"] = round(pfx.get("ttft_warm_ms", 0.0), 1)
        out["ttft_cold_ms"] = round(pfx.get("ttft_cold_ms", 0.0), 1)
        out["prefix_hit_tokens"] = int(pfx.get("hit_tokens", 0))
        out["prefix_hit_rate"] = round(pfx.get("hit_rate", 0.0), 3)
        out["prefix_warm_speedup"] = round(pfx.get("speedup", 0.0), 2)
    rec = RESULTS.get("reconnect")
    if rec:
        # session leases + reconnect-resume: recovery stall + replayed
        # tokens when the client's connection is severed mid-decode, with
        # resume on (re-attach the lease-parked session, retransmit one
        # step, zero prompt replay) vs off (full history replay)
        out["reconnect_stall_resume_ms"] = round(
            rec.get("stall_resume_ms", 0.0), 1
        )
        out["reconnect_stall_replay_ms"] = round(
            rec.get("stall_replay_ms", 0.0), 1
        )
        out["reconnect_replayed_tokens_resume"] = int(
            rec.get("replayed_resume", 0)
        )
        out["reconnect_replayed_tokens_full"] = int(
            rec.get("replayed_full", 0)
        )
        out["reconnect_steps_deduped"] = int(rec.get("steps_deduped", 0))
        out["reconnect_sessions_resumed"] = int(
            rec.get("sessions_resumed", 0)
        )
    fo = RESULTS.get("failover")
    if fo:
        # standby-KV replication: recovery stall + replayed tokens when a
        # primary dies mid-decode, with replication on vs off (full replay)
        out["failover_stall_repl_ms"] = round(fo.get("stall_repl_ms", 0.0), 1)
        out["failover_stall_replay_ms"] = round(
            fo.get("stall_replay_ms", 0.0), 1
        )
        out["failover_replayed_tokens_repl"] = int(
            fo.get("replayed_repl", 0)
        )
        out["failover_replayed_tokens_full"] = int(
            fo.get("replayed_full", 0)
        )
    itf = RESULTS.get("interference")
    if itf:
        # stall-free scheduling: decode time-between-tokens while a long
        # prompt prefills concurrently (the multi-tenant tail next to
        # ttft_ms above), chunked vs monolithic prefill
        ch = itf.get("chunked") or {}
        mono = itf.get("monolithic") or {}
        out["tbt_p50_ms"] = round(ch.get("tbt_p50_ms", 0.0), 1)
        out["tbt_p95_ms"] = round(ch.get("tbt_p95_ms", 0.0), 1)
        out["tbt_p95_monolithic_ms"] = round(mono.get("tbt_p95_ms", 0.0), 1)
        out["tbt_p95_stall_free_speedup"] = round(
            itf.get("tbt_p95_speedup", 0.0), 2
        )
        out["interference_prefill_chunks"] = int(
            ch.get("prefill_chunks", 0)
        )
        out["interference_decode_steps_interleaved"] = int(
            ch.get("decode_steps_interleaved", 0)
        )
        # mixed-batch dispatch: decodes fused INTO the prefill chunk's
        # device step — fewer dispatches per generated token than the
        # interleaved-but-separate chunked schedule
        mx = itf.get("mixed") or {}
        out["dispatches_per_token"] = round(
            ch.get("dispatches_per_token", 0.0), 4
        )
        out["dispatches_per_token_mixed"] = round(
            mx.get("dispatches_per_token", 0.0), 4
        )
        out["dispatches_per_token_reduction"] = round(
            itf.get("dispatches_per_token_reduction", 0.0), 2
        )
        out["mixed_dispatches"] = int(mx.get("mixed_dispatches", 0))
        out["mixed_batch_mean_width"] = round(
            mx.get("mixed_tokens", 0)
            / max(mx.get("mixed_dispatches", 0), 1),
            2,
        )
        out["tbt_p95_mixed_ms"] = round(mx.get("tbt_p95_ms", 0.0), 1)
        # universal ragged dispatch: the same contention plus a
        # speculative stream — decode + tree-verify + chunk rows in ONE
        # device step vs the mixed-only baseline where tree rounds
        # dispatch solo
        uni = itf.get("universal") or {}
        ub = itf.get("universal_baseline") or {}
        if uni:
            out["dispatches_per_token_universal"] = round(
                uni.get("dispatches_per_token", 0.0), 4
            )
            out["dispatches_per_token_universal_baseline"] = round(
                ub.get("dispatches_per_token", 0.0), 4
            )
            out["universal_dispatches_per_token_reduction"] = round(
                itf.get("universal_dispatches_per_token_reduction", 0.0), 2
            )
            out["tbt_p95_universal_ms"] = round(
                uni.get("tbt_p95_ms", 0.0), 1
            )
            out["ragged_cross_kind_dispatches"] = int(
                uni.get("ragged_cross_kind_dispatches", 0)
            )
    msb = RESULTS.get("multisession_batched")
    if msb:
        # continuous batching: aggregate throughput + how wide the merged
        # decode dispatches actually ran, and the dispatch amortization
        out["batched_agg_equiv_tok_per_s"] = round(
            msb.get("agg_equiv_tok_per_s", 0.0), 1
        )
        out["batched_mean_width"] = round(
            msb.get("mean_batch_width", 0.0), 2
        )
        out["batched_dispatches_per_token"] = round(
            msb.get("dispatches_per_token", 0.0), 4
        )
    ovl = RESULTS.get("overload")
    if ovl:
        # overload protection: with admission + load-aware routing ON the
        # hard-failure count must be zero (everything completes or is shed
        # retriably) and light-session TBT stays bounded vs OFF
        on = ovl.get("protected") or {}
        off = ovl.get("unprotected") or {}
        out["overload_hard_failures_protected"] = int(
            on.get("hard_failures", 0)
        )
        out["overload_hard_failures_unprotected"] = int(
            off.get("hard_failures", 0)
        )
        out["overload_sheds"] = int(on.get("sheds", 0))
        out["overload_light_tbt_p95_protected_ms"] = round(
            on.get("tbt_p95_ms", 0.0), 1
        )
        out["overload_light_tbt_p95_unprotected_ms"] = round(
            off.get("tbt_p95_ms", 0.0), 1
        )
        out["overload_light_share_protected"] = round(
            on.get("light_share", 0.0), 3
        )
        out["overload_light_share_unprotected"] = round(
            off.get("light_share", 0.0), 3
        )
    asc = RESULTS.get("autoscale")
    if asc:
        # elastic self-healing: light-session decode TBT under a shifting
        # heavy-prefill load with the standby control loop ON (promotes,
        # absorbs the flood) vs OFF (same two processes, watermark parked
        # at infinity), plus the kill-recovery leg: primary killed
        # mid-generation, the client rides the dark window onto the
        # promoted standby and the resumed tokens match an uninterrupted
        # run exactly
        el = asc.get("elastic") or {}
        st = asc.get("static") or {}
        out["autoscale_tbt_p95_elastic_ms"] = round(
            el.get("tbt_p95_ms", 0.0), 1
        )
        out["autoscale_tbt_p95_static_ms"] = round(
            st.get("tbt_p95_ms", 0.0), 1
        )
        out["autoscale_tbt_p95_speedup"] = round(
            asc.get("tbt_p95_speedup", 0.0), 2
        )
        out["autoscale_promotions"] = int(el.get("promotions", 0))
        out["autoscale_hard_failures"] = int(
            el.get("hard_failures", 0) + st.get("hard_failures", 0)
        )
        rec = asc.get("recovery") or {}
        out["autoscale_recover_stall_ms"] = round(
            rec.get("stall_ms", 0.0), 1
        )
        out["autoscale_token_identical"] = bool(
            rec.get("token_identical", False)
        )
        out["autoscale_recover_hard_failures"] = int(
            rec.get("hard_failures", 0)
        )
        out["autoscale_recover_promotions"] = int(
            rec.get("promotions", 0)
        )
        # zero-cold-start recovery: promotion-to-first-token with the
        # swarm-shared compile-artifact cache pre-installed on the standby
        # vs the cold local-compile baseline (in-memory jit cache cleared
        # at the promotion boundary in BOTH variants, so the delta is
        # exactly what pre-install buys a fresh process)
        pre = asc.get("recovery_preinstall") or {}
        out["autoscale_promotion_to_first_token_cold_ms"] = round(
            rec.get("first_token_ms", 0.0), 1
        )
        out["autoscale_promotion_to_first_token_preinstall_ms"] = round(
            pre.get("first_token_ms", 0.0), 1
        )
        out["autoscale_artifact_preinstalled"] = bool(
            pre.get("preinstalled", False)
        )
        out["autoscale_preinstall_token_identical"] = bool(
            pre.get("token_identical", False)
        )
    sim = RESULTS.get("swarm_sim")
    if sim:
        # swarm-scale simulation (virtual clock, real control plane over
        # the calibrated cost model — no device work): post-perturbation
        # convergence and client-measured retry amplification, so
        # control-plane regressions surface in the same JSON the device
        # phases do. The blocking gate is `python -m bloombee_tpu.sim
        # --require` in chaos.sh; here the numbers just ride along.
        for scen, sm in sim.items():
            out[f"sim_{scen}_completed"] = int(sm.get("completed", 0))
            out[f"sim_{scen}_retry_amp"] = round(
                sm.get("retry_amplification", 0.0), 2
            )
            out[f"sim_{scen}_converged_at_s"] = round(
                sm.get("shed_rate_converged_at_s", 0.0), 1
            )
            out[f"sim_{scen}_gate_failures"] = len(
                sm.get("gate_failures") or []
            )
    if RESULTS.get("phases"):
        out["phases"] = RESULTS["phases"]
    if RESULTS.get("compile_stats"):
        # per-phase XLA compile counts/ms (jitwatch): a phase whose count
        # grows run over run is a recompile storm, attributable here
        # instead of showing up only as degraded rates
        out["compile_stats"] = RESULTS["compile_stats"]
    if RESULTS.get("cpu_fallback"):
        # scrub EVERY rate/latency key, not just the headline: a consumer
        # plotting any per-second number must not ingest CPU-smoke rates
        # as measurements. The raw smoke values move to cpu_smoke_rates as
        # the code-readiness record.
        keep = {"server_decode_chunk", "server_decode_chain_chunk"}
        smoke = {}
        for key, val in list(out.items()):
            if (
                isinstance(val, (int, float))
                and not isinstance(val, bool)
                and key not in keep
            ):
                smoke[key] = val
                out[key] = 0.0
        out["cpu_smoke_rates"] = smoke
        out["cpu_fallback"] = True
    if RESULTS.get("degraded"):
        out["degraded"] = RESULTS["degraded"]
    # single machine-checkable flag for blind tunnel-attached runs: any
    # backend fallback OR phase degradation means the numbers are not a
    # clean measurement (automated consumers key on this, not on parsing
    # the free-text `degraded` reason)
    out["backend_degraded"] = bool(
        RESULTS.get("cpu_fallback") or RESULTS.get("degraded")
    )
    # preflight verdict, stamped before any phase ran: True means the
    # tunnel was already dead at bench start (see run_preflight) — a
    # watchdog-partial or empty ledger with tunnel_down=True is a tunnel
    # outage, not a code failure
    out["tunnel_down"] = bool(RESULTS.get("tunnel_down"))
    print(json.dumps(out), flush=True)


def start_watchdog():
    """Emit whatever has been measured and exit 0 if the run exceeds the
    deadline (a wedged PJRT transfer cannot be interrupted, only abandoned)."""
    deadline_s = float(env.get("BBTPU_BENCH_DEADLINE_S"))

    def watch():
        if not _DONE.wait(deadline_s):
            RESULTS.setdefault(
                "degraded", f"watchdog fired after {deadline_s:.0f}s "
                "(backend wedged mid-phase); partial results"
            )
            log(f"WATCHDOG: bench exceeded {deadline_s:.0f}s — emitting "
                "partial results")
            emit_json()
            os._exit(0)

    threading.Thread(target=watch, daemon=True).start()


_PREFLIGHT_DEGRADED = (
    "tunnel preflight failed: no usable jax backend at bench start "
    "(tunnel_down)"
)


def run_preflight() -> bool:
    """Cheap tunnel-health probe BEFORE the phase ledger: one short
    subprocess backend init (a dead tunnel blocks PJRT init forever, so
    never probe in-process). A failure stamps tunnel_down +
    backend_degraded into the JSON up front — even a watchdog-partial
    run then says WHY it is empty instead of leaving a bare rc to
    disambiguate. _require_backend still rides out the outage afterwards
    with its full retry budget; if it recovers, the preflight verdict is
    amended rather than left stale."""
    import subprocess

    phase("preflight", "started")
    probe_code = (
        "import os, jax\n"
        "if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "print(len(jax.devices()))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe_code],
            timeout=45.0, capture_output=True, text=True,
            env=os.environ.copy(),
        )
        ok = proc.returncode == 0 and proc.stdout.strip().isdigit()
        detail = proc.stderr.strip()[-200:]
    except subprocess.TimeoutExpired:
        ok, detail = False, "probe timed out (wedged tunnel?)"
    if ok:
        phase("preflight", "ok")
        return True
    log(f"preflight: tunnel DOWN at bench start ({detail})")
    phase("preflight", "tunnel_down")
    RESULTS["tunnel_down"] = True
    RESULTS.setdefault("degraded", _PREFLIGHT_DEGRADED)
    return False


def _preflight_recovered() -> None:
    """The backend came up after a failed preflight: amend the up-front
    tunnel_down stamp so a recovered run isn't reported as degraded for
    an outage it rode out."""
    if not RESULTS.get("tunnel_down"):
        return
    RESULTS["tunnel_down"] = False
    phase("preflight", "tunnel_down_recovered")
    if RESULTS.get("degraded") == _PREFLIGHT_DEGRADED:
        del RESULTS["degraded"]


def _require_backend():
    """Wait for a usable JAX backend, retrying with backoff instead of
    failing fast: the tunnel-attached TPU goes down for stretches, and a
    round whose bench happens to start during one must still capture a
    number if the tunnel recovers within the deadline.

    Probing runs in SUBPROCESSES: PJRT backend init on a dead tunnel blocks
    forever with no way to interrupt it, and a wedged init would poison this
    process's global backend state even after the tunnel recovers. Only
    after a probe subprocess succeeds do we init the backend in-process.

    If the tunnel never comes up within the probe budget, fall back to a
    CPU SMOKE run: the numbers are meaningless (flagged degraded +
    cpu_fallback) but the phase ledger then records which phases are
    CODE-READY — a bare rc=3 is indistinguishable from missing phases
    (round-4 verdict #1)."""
    import subprocess

    deadline_s = float(env.get("BBTPU_BENCH_DEADLINE_S"))
    # probe for up to half the deadline (an explicit long deadline means
    # "ride out the outage" — honor it), but always leave ~700s so the
    # CPU-smoke fallback can complete its phase ledger
    budget = max(120.0, min(deadline_s / 2, deadline_s - 700.0))
    t_start = time.time()
    attempt = 0
    while True:
        attempt += 1
        left = budget - (time.time() - t_start)
        if left <= 0:
            if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
                # already an explicit CPU run that somehow failed probing
                RESULTS.setdefault(
                    "degraded",
                    f"no usable jax backend within {budget:.0f}s; "
                    "no phases ran",
                )
                emit_json()
                os._exit(3)
            log(
                f"no TPU backend within {budget:.0f}s ({attempt - 1} "
                "probes); falling back to CPU SMOKE for a code-readiness "
                "phase ledger"
            )
            RESULTS["degraded"] = (
                f"tpu tunnel unreachable for {budget:.0f}s; phases ran "
                "as CPU smoke — values are NOT performance numbers, the "
                "phase ledger records code readiness only"
            )
            RESULTS["cpu_fallback"] = True
            os.environ["BBTPU_BENCH_SMOKE"] = "1"
            import jax

            jax.config.update("jax_platforms", "cpu")
            phase("backend", "cpu_fallback")
            return
        # the image's sitecustomize force-registers the TPU platform and
        # ignores the JAX_PLATFORMS env var; honor an explicit cpu request
        # inside the probe the same way main() does
        probe_code = (
            "import os, jax\n"
            "if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':\n"
            "    jax.config.update('jax_platforms', 'cpu')\n"
            "print(len(jax.devices()))\n"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe_code],
                timeout=min(120.0, left), capture_output=True, text=True,
                env=os.environ.copy(),
            )
            if proc.returncode == 0 and proc.stdout.strip().isdigit():
                log(f"backend probe ok after {attempt} attempt(s) "
                    f"({time.time() - t_start:.0f}s): "
                    f"{proc.stdout.strip()} device(s)")
                _preflight_recovered()
                return
            log(f"backend probe attempt {attempt} failed "
                f"(rc={proc.returncode}): {proc.stderr.strip()[-200:]}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {attempt} timed out "
                "(tunnel down?); retrying")
        time.sleep(min(30.0, 5.0 * attempt))


def main():
    start_watchdog()
    # the bench always runs under the compile witness: per-phase compile
    # deltas ride the BENCH JSON (opt-out by exporting BBTPU_JITWATCH=0)
    os.environ.setdefault("BBTPU_JITWATCH", "1")
    from bloombee_tpu.utils import jitwatch

    jitwatch.install()
    # the image's sitecustomize force-registers the TPU platform; honor an
    # explicit JAX_PLATFORMS=cpu (smoke/CI runs) the same way dryrun does
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    run_preflight()
    _require_backend()
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bloombee_tpu.kv.arena import make_arena
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.step import pack_plan, span_step_impl
    from bloombee_tpu.utils.tree import stack_params

    # one span = 8 of Llama-3-8B's 32 layers
    smoke = bool(env.get("BBTPU_BENCH_SMOKE"))
    span_layers, total_layers = 8, 32
    spec = ModelSpec(
        family="llama",
        hidden_size=256 if smoke else 4096,
        intermediate_size=512 if smoke else 14336,
        num_attention_heads=8 if smoke else 32,
        num_key_value_heads=4 if smoke else 8,
        head_dim=32 if smoke else 128,
        num_hidden_layers=span_layers,
        vocab_size=1024 if smoke else 128256,
    )
    B, PREFILL, DECODE = 8, 128, (8 if smoke else 64)
    page_size, num_pages = 16, 128
    max_pages = 16  # 256-token bucket
    if smoke:
        log("SMOKE MODE: tiny dims; numbers are meaningless")

    if RESULTS.get("phases", {}).get("backend") != "cpu_fallback":
        phase("backend", "ok")
    log(f"devices: {jax.devices()}")
    phase("fused_proxy", "started")
    params = stack_params(
        [
            init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.bfloat16)
            for i in range(span_layers)
        ]
    )
    arena = make_arena(
        span_layers, num_pages, page_size, spec.num_key_value_heads,
        spec.head_dim, jnp.bfloat16,
    )

    pages_per_seq = (PREFILL + DECODE + page_size - 1) // page_size
    page_table = np.zeros((B, max_pages), np.int32)
    for i in range(B):
        page_table[i, :pages_per_seq] = np.arange(
            i * pages_per_seq, (i + 1) * pages_per_seq
        )

    def slots_for(positions):  # positions [B, T]
        page = page_table[
            np.arange(B)[:, None], positions // page_size
        ]
        return (page * page_size + positions % page_size).reshape(-1)

    # ---- prefill (one span_step call, T=PREFILL)
    pre_pos = np.broadcast_to(np.arange(PREFILL)[None], (B, PREFILL))
    pre_plan = pack_plan(
        slots_for(pre_pos),
        page_table,
        pre_pos,
        np.full((B,), PREFILL, np.int32),
        np.ones((span_layers,), np.int32),
    )
    hidden0 = jax.random.normal(
        jax.random.PRNGKey(42), (B, PREFILL, spec.hidden_size), jnp.bfloat16
    ) * 0.02

    def fence(x) -> float:
        """Force full materialization: block_until_ready is unreliable on
        tunneled PJRT backends, so fetch a scalar reduction to host."""
        return float(jnp.sum(x.astype(jnp.float32)))

    step = jax.jit(
        lambda p, ak, av, h, plan: span_step_impl(
            p, ak, av, h, plan, None,
            spec=spec, page_size=page_size, max_pages=max_pages,
        ),
        donate_argnums=(1, 2),
    )
    t0 = time.time()
    h, ak, av = step(params, arena["k"], arena["v"], hidden0, jnp.asarray(pre_plan))
    fence(h)
    log(f"prefill({B}x{PREFILL}) compile+run: {time.time()-t0:.1f}s")
    # calibrate the fence cost itself (dispatch + scalar d2h latency)
    t0 = time.time()
    for _ in range(3):
        fence(h)
    fence_cost = (time.time() - t0) / 3
    log(f"fence cost: {fence_cost*1000:.1f} ms")
    RESULTS["fence_ms"] = fence_cost * 1000.0

    # ---- fused decode: one jitted scan over per-step plans
    plans = []
    for s in range(DECODE):
        pos = np.full((B, 1), PREFILL + s, np.int32)
        plans.append(
            pack_plan(
                slots_for(pos), page_table, pos,
                np.full((B,), PREFILL + s + 1, np.int32),
                np.ones((span_layers,), np.int32),
            )
        )
    plans = jnp.asarray(np.stack(plans))  # [N, plan_len]

    def decode_many(params, ak, av, h_last, plans):
        def body(carry, plan):
            h, ak, av = carry
            h, ak, av = span_step_impl(
                params, ak, av, h, plan, None,
                spec=spec, page_size=page_size, max_pages=max_pages,
            )
            return (h, ak, av), None

        (h, ak, av), _ = lax.scan(body, (h_last, ak, av), plans)
        return h, ak, av

    decode_jit = jax.jit(decode_many, donate_argnums=(1, 2))

    h_last = h[:, -1:, :]
    t0 = time.time()
    h2, ak, av = decode_jit(params, ak, av, h_last, plans)
    fence(h2)
    log(f"decode scan compile+run: {time.time()-t0:.1f}s")

    # steady state: chain REPEAT scans (overwrites same cache slots; same
    # compute), one fence at the end, fence cost subtracted
    REPEAT = 4
    t0 = time.time()
    for _ in range(REPEAT):
        h2, ak, av = decode_jit(params, ak, av, h_last, plans)
    fence(h2)
    elapsed = max(time.time() - t0 - fence_cost, 1e-9)
    total_steps = DECODE * REPEAT

    # timing prefill again post-compile for TTFT
    t0 = time.time()
    h3, ak, av = step(params, ak, av, hidden0, jnp.asarray(pre_plan))
    fence(h3)
    ttft = max(time.time() - t0 - fence_cost, 0.0)

    steps_per_sec = total_steps / elapsed
    batch_tok_per_sec = steps_per_sec * B
    spans_per_model = total_layers // span_layers
    equiv_per_seq = steps_per_sec / spans_per_model
    equiv_batch = batch_tok_per_sec / spans_per_model
    RESULTS["proxy_equiv_per_seq"] = equiv_per_seq
    phase("fused_proxy", "ok")
    log(
        f"fused-scan proxy: {steps_per_sec:.1f} steps/s; 8B-equiv per-seq "
        f"{equiv_per_seq:.1f} tok/s, batch({B}) {equiv_batch:.0f} tok/s; "
        f"prefill(ttft proxy) {ttft*1000:.0f} ms"
    )

    # ---- long-context phase: paged Pallas kernel vs dense gather at 4k
    # (committed harness for the paged kernel's headline win; previously
    # only an ad-hoc loop in git history)
    try:
        phase("longctx", "started")
        run_longctx(spec, params, B, smoke)  # marks itself ok/skipped
    except Exception as e:  # noqa: BLE001
        phase("longctx", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"longctx phase failed: {e!r}")
        log(f"longctx phase FAILED: {e!r}")

    # the span params + arena of the proxy phase were donated away; the
    # served phase builds its own server-side state from `params`
    try:
        # run_served publishes its result dict into RESULTS itself (phase by
        # phase) so the watchdog sees partials; the return is for logging
        served = run_served(spec, params, B, PREFILL, DECODE, spans_per_model)
        log(
            f"served: {served['steps_per_sec']:.1f} steps/s; 8B-equiv per-seq "
            f"{served['equiv_per_seq']:.1f} tok/s, batch({B}) "
            f"{served['equiv_per_seq'] * B:.0f} tok/s; ttft "
            f"{served['ttft_ms']:.0f}"
            f" ms; effective({served['n_sessions']} sessions x batch {B}) "
            f"{served['effective_equiv_tok_per_s']:.0f} 8B-equiv tok/s; "
            f"timing {served['timing']}"
        )
    except Exception as e:  # noqa: BLE001 — degrade, never lose the JSON line
        RESULTS.setdefault("degraded", f"served phase failed: {e!r}")
        log(f"served phase FAILED: {e!r}")

    # ---- prefix-cache phase: N sessions sharing a multi-page system
    # prompt against a --prefix-cache server; warm sessions probe the pool
    # and ship only the uncached suffix, so warm TTFT drops to roughly the
    # suffix's share of the prefill
    try:
        phase("prefix_cache", "started")
        run_prefix_cache(spec, params)
    except Exception as e:  # noqa: BLE001
        phase("prefix_cache", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"prefix_cache phase failed: {e!r}")
        log(f"prefix_cache phase FAILED: {e!r}")

    # ---- failover phase: kill the primary mid-decode and measure the
    # recovery stall + replayed tokens with standby-KV replication on
    # (probe-and-skip onto the standby's replicated pages) vs off (full
    # history replay)
    try:
        phase("failover", "started")
        run_failover(spec, params)
    except Exception as e:  # noqa: BLE001
        phase("failover", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"failover phase failed: {e!r}")
        log(f"failover phase FAILED: {e!r}")

    # ---- reconnect phase: sever the client's connection mid-decode and
    # measure the recovery stall + replayed tokens with reconnect-resume
    # on (re-attach the lease-parked session, retransmit ONE step under
    # its original id) vs off (full history replay onto a fresh session)
    try:
        phase("reconnect", "started")
        run_reconnect(spec, params)
    except Exception as e:  # noqa: BLE001
        phase("reconnect", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"reconnect phase failed: {e!r}")
        log(f"reconnect phase FAILED: {e!r}")

    # ---- interference phase: decode TBT (time-between-tokens) for N
    # sessions while a long prompt prefills concurrently on the same
    # server — chunked (stall-free) vs monolithic prefill. The number a
    # multi-tenant user actually feels when a neighbor pastes a document.
    try:
        phase("interference", "started")
        run_interference(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("interference", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"interference phase failed: {e!r}")
        log(f"interference phase FAILED: {e!r}")

    # ---- overload phase: clients > capacity. With admission control +
    # load-aware routing ON, every request must complete or be shed with a
    # retriable `overloaded` (zero hard failures) and established light
    # sessions' decode TBT stays bounded; OFF is the queue-behind-the-flood
    # baseline.
    try:
        phase("overload", "started")
        run_overload(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("overload", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"overload phase failed: {e!r}")
        log(f"overload phase FAILED: {e!r}")

    # ---- autoscale phase: elastic self-healing under a shifting hot
    # load. With the standby control loop ON the standby promotes when
    # the primary's advertised queue delay crosses the watermark and
    # absorbs the heavy flood (light decode TBT p95 must beat the same
    # topology with the loop OFF); the kill-recovery leg then kills the
    # primary mid-generation and requires a token-identical resume via
    # standby promotion with zero hard session failures.
    try:
        phase("autoscale", "started")
        run_autoscale(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("autoscale", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"autoscale phase failed: {e!r}")
        log(f"autoscale phase FAILED: {e!r}")

    # ---- spec_decode phase: N concurrent speculating sessions. Solo mode
    # pays one device dispatch per session per tree round; --spec-batch
    # coalesces concurrent rounds into grouped ragged dispatches, so
    # dispatches per committed token drops with session count.
    try:
        phase("spec_decode", "started")
        run_spec_decode(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("spec_decode", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"spec_decode phase failed: {e!r}")
        log(f"spec_decode phase FAILED: {e!r}")

    # ---- integrity phase: Byzantine robustness. Three replicas, one a
    # LIAR returning well-formed replies with perturbed hidden states;
    # the client's sanity gate + cross-replica audits must quarantine it
    # within the decode budget while the generation stays token-identical
    # to a clean reference (every lie caught BEFORE its token commits),
    # with zero hard failures and zero clean-swarm false positives.
    try:
        phase("integrity", "started")
        run_integrity(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("integrity", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"integrity phase failed: {e!r}")
        log(f"integrity phase FAILED: {e!r}")

    # ---- wire phase: bytes/token, codec ms/step, and decode-step p50/p95
    # under the chaos DELAY matrix — off-loop codec pipeline on vs off vs
    # a legacy (pre-negotiation, sync-codec) peer, token-identical across
    # all legs
    try:
        phase("wire", "started")
        run_wire(spec, params, smoke)
    except Exception as e:  # noqa: BLE001
        phase("wire", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"wire phase failed: {e!r}")
        log(f"wire phase FAILED: {e!r}")

    # ---- swarm_sim phase: the traffic simulator's scenario sweep at
    # smoke size (virtual clock, real control plane, zero device work) —
    # flash crowd, correlated span loss, diurnal ramp — so the
    # metastability metrics land in the bench JSON next to the device
    # numbers they ultimately protect
    try:
        phase("swarm_sim", "started")
        run_swarm_sim()
    except Exception as e:  # noqa: BLE001
        phase("swarm_sim", f"failed: {e!r}"[:200])
        RESULTS.setdefault("degraded", f"swarm_sim phase failed: {e!r}")
        log(f"swarm_sim phase FAILED: {e!r}")

    # value: SERVED full-model-equivalent PER-SEQUENCE decode tok/s (batch 8
    # session through registry + BlockServer + wire); baseline 35 tok/s =
    # single-A100 single-stream HF decode on Llama-3-8B (BASELINE.md).
    # Extra keys: the on-device fused-scan ceiling and the multi-session
    # effective throughput (per-seq is floored by the host<->device round
    # trip, ~70-100 ms on this tunnel-attached chip; concurrent sessions
    # overlap those round trips).
    _DONE.set()
    emit_json()


def run_longctx(spec, params, B, smoke: bool) -> None:
    """Decode at long context: paged Pallas kernel (one HBM pass over K/V
    pages) vs the dense gather-then-attend path (two passes). Both run the
    SAME jitted span step with only the use_paged flag flipped; timing is a
    chain of async dispatches fenced once (dispatch is async on this
    backend, so wall time == device time once the queue is primed)."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.arena import make_arena
    from bloombee_tpu.runtime.step import (
        pack_plan,
        pack_step_payload,
        span_step_packed,
    )
    from bloombee_tpu.utils import env as _env

    interpret = _env.get("BBTPU_PAGED_INTERPRET")
    if jax.default_backend() != "tpu" and not interpret:
        phase("longctx", "skipped: no TPU backend (set "
              "BBTPU_PAGED_INTERPRET to force)")
        return
    CTX = 256 if smoke else 4096
    page_size = 16
    span_layers = spec.num_hidden_layers
    pages_per_seq = (CTX + 1 + page_size - 1) // page_size + 1
    pb = 1
    while pb < pages_per_seq:
        pb *= 2
    num_pages = B * pb
    arena = make_arena(
        span_layers, num_pages, page_size, spec.num_key_value_heads,
        spec.head_dim, jnp.bfloat16,
    )
    # context KV contents don't matter for timing; leave the arena zeroed
    # and declare every row CTX tokens long
    page_table = np.zeros((B, pb), np.int32)
    for i in range(B):
        page_table[i] = np.arange(i * pb, (i + 1) * pb)
    slot = (
        page_table[:, CTX // page_size] * page_size + CTX % page_size
    ).reshape(B, 1)
    positions = np.full((B, 1), CTX, np.int32)
    lens = np.full((B,), CTX + 1, np.int32)
    plan = pack_plan(
        slot, page_table, positions, lens, np.ones((span_layers,), np.int32)
    )
    import ml_dtypes

    rng = np.random.default_rng(1)
    h = (rng.standard_normal((B, 1, spec.hidden_size)) * 0.02).astype(
        ml_dtypes.bfloat16
    )
    payload = jnp.asarray(pack_step_payload(h, plan))

    def fence(x) -> float:
        return float(jnp.sum(x.astype(jnp.float32)))

    results = {}
    steps = 4 if smoke else 32
    # third variant: the int4-quantized arena through the in-VMEM-dequant
    # paged kernel — never yet timed on real TPU hardware (round-4
    # verdict: the quantized serving claim is untested until it is)
    arena_q = None
    for name, use_paged in (
        ("dense", False), ("paged", True), ("paged_int4", True)
    ):
        try:
            if name == "paged_int4":
                # allocate only now: a second full arena held during the
                # dense/paged timings would double KV memory (allocator
                # pressure skews their numbers and can OOM large contexts)
                arena_q = make_arena(
                    span_layers, num_pages, page_size,
                    spec.num_key_value_heads, spec.head_dim, jnp.bfloat16,
                    quant="int4",
                )
            cur = arena_q if name == "paged_int4" else arena
            ak, av = cur["k"], cur["v"]
            t0 = time.time()
            out, ak, av = span_step_packed(
                params, ak, av, payload, None, None,
                spec=spec, b=B, t=1, page_size=page_size, max_pages=pb,
                use_paged=use_paged,
                windows=tuple(0 for _ in range(span_layers)),
            )
            fence(out)
            log(f"longctx {name} compile+run: {time.time()-t0:.1f}s")
            t0 = time.time()
            for _ in range(steps):
                out, ak, av = span_step_packed(
                    params, ak, av, payload, None, None,
                    spec=spec, b=B, t=1, page_size=page_size, max_pages=pb,
                    use_paged=use_paged,
                    windows=tuple(0 for _ in range(span_layers)),
                )
            fence(out)
            dt = max(
                time.time() - t0 - RESULTS.get("fence_ms", 0.0) / 1e3, 1e-9
            )
            results[name] = steps / dt
            # donation consumed the inputs; carry the outputs forward
            if name == "paged_int4":
                arena_q = {"k": ak, "v": av}
            else:
                arena = {"k": ak, "v": av}
            phase(f"longctx_{name}", "ok")
        except Exception as e:  # noqa: BLE001 — one variant must not sink
            # the rest, but a failed variant IS a degraded run: automated
            # consumers key on 'degraded', not on a zero-valued metric
            phase(f"longctx_{name}", f"failed: {e!r}"[:200])
            RESULTS.setdefault("degraded", f"longctx {name} failed: {e!r}")
            log(f"longctx {name} FAILED: {e!r}")
    if "paged" in results and "dense" in results:
        results["speedup"] = results["paged"] / max(results["dense"], 1e-9)
        log(
            f"longctx ctx={CTX}: paged {results['paged']:.1f} steps/s vs "
            f"dense {results['dense']:.1f} steps/s "
            f"({results['speedup']:.2f}x)"
        )
    if "paged_int4" in results:
        log(f"longctx ctx={CTX}: paged_int4 {results['paged_int4']:.1f} "
            "steps/s")

    # --- tree-verify step (T=8 speculative tokens) at long context: the
    # chunk kernel (one HBM pass, tree mask in-kernel) vs the dense
    # gather-then-attend path — the speculative hot path's verify cost
    # (round-4 verdict #5 bench criterion)
    T8 = 8
    pos8 = np.broadcast_to(
        CTX + np.arange(T8, dtype=np.int32)[None], (B, T8)
    )
    slot8 = (
        page_table[np.arange(B)[:, None], pos8 // page_size] * page_size
        + pos8 % page_size
    )
    plan8 = pack_plan(
        slot8, page_table, pos8, np.full((B,), CTX + T8, np.int32),
        np.ones((span_layers,), np.int32),
    )
    tm8 = np.tril(np.ones((T8, T8), bool))  # chain tree: ancestors visible
    tm8 = np.broadcast_to(tm8, (B, T8, T8)).copy()
    h8 = (rng.standard_normal((B, T8, spec.hidden_size)) * 0.02).astype(
        ml_dtypes.bfloat16
    )
    payload8 = jnp.asarray(pack_step_payload(h8, plan8))
    tm8_dev = jnp.asarray(tm8)
    for name, use_paged in (("tree8_dense", False), ("tree8_paged", True)):
        try:
            ak, av = arena["k"], arena["v"]
            t0 = time.time()
            out, ak, av = span_step_packed(
                params, ak, av, payload8, tm8_dev, None,
                spec=spec, b=B, t=T8, page_size=page_size, max_pages=pb,
                use_tree_mask=True, use_paged=use_paged,
                windows=tuple(0 for _ in range(span_layers)), t_real=T8,
            )
            fence(out)
            log(f"longctx {name} compile+run: {time.time()-t0:.1f}s")
            t0 = time.time()
            for _ in range(steps):
                out, ak, av = span_step_packed(
                    params, ak, av, payload8, tm8_dev, None,
                    spec=spec, b=B, t=T8, page_size=page_size,
                    max_pages=pb, use_tree_mask=True, use_paged=use_paged,
                    windows=tuple(0 for _ in range(span_layers)),
                    t_real=T8,
                )
            fence(out)
            dt = max(
                time.time() - t0 - RESULTS.get("fence_ms", 0.0) / 1e3, 1e-9
            )
            results[name] = steps / dt
            arena = {"k": ak, "v": av}
            phase(f"longctx_{name}", "ok")
        except Exception as e:  # noqa: BLE001
            phase(f"longctx_{name}", f"failed: {e!r}"[:200])
            RESULTS.setdefault("degraded", f"longctx {name} failed: {e!r}")
            log(f"longctx {name} FAILED: {e!r}")
    if "tree8_paged" in results and "tree8_dense" in results:
        results["tree8_speedup"] = results["tree8_paged"] / max(
            results["tree8_dense"], 1e-9
        )
        log(
            f"longctx ctx={CTX} tree8: paged {results['tree8_paged']:.1f} "
            f"vs dense {results['tree8_dense']:.1f} verify-steps/s "
            f"({results['tree8_speedup']:.2f}x)"
        )
    RESULTS["ctx4k"] = results
    required = {"dense", "paged", "paged_int4", "tree8_dense", "tree8_paged"}
    phase(
        "longctx",
        "ok" if required <= set(results)
        else "partial (see longctx_* phases)",
    )


def run_prefix_cache(spec, params) -> None:
    """Cross-session shared-prefix phase: sessions share a 6-page system
    prompt; the first (cold) session computes and publishes it, later
    (warm) sessions adopt the pooled pages and prefill only their 8-token
    tails. Reports cold vs warm TTFT and the server's hit accounting."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    SYS, TAIL = 6 * PAGE, 8  # shared pages + per-session unique suffix
    N_WARM = 4
    # ids only feed hash chains + a deterministic embedding; a small id
    # range keeps the host-side embed table tiny at real vocab sizes
    VOCAB_EFF = min(1024, spec.vocab_size)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="bench_pfx", start=0, end=span_layers, params=params,
            spec=spec, registry=rc(), num_pages=256, page_size=PAGE,
            max_batch=1, prefix_cache=True,
        )
        await server.start()
        manager = RemoteSequenceManager(rc(), "bench_pfx", span_layers)
        rng = np.random.default_rng(7)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)
        sys_ids = rng.integers(0, VOCAB_EFF, size=(SYS,))

        async def one_prefill(ids_row) -> float:
            ids = np.asarray(ids_row, dtype=np.int64)[None]  # [1, S]
            hidden = embed_table[ids]
            s = InferenceSession(
                manager, max_length=ids.shape[1] + 4, batch_size=1,
                prefix_cache=True,
            )
            async with s:
                t0 = time.time()
                await s.step(hidden, ids=ids)
                return (time.time() - t0) * 1000.0

        try:
            # untimed: compile the full-prompt prefill bucket on a prompt
            # that shares nothing, then time a true cold run on the shared
            # system prompt (which also publishes its pages)
            await one_prefill(rng.integers(0, VOCAB_EFF, size=(SYS + TAIL,)))
            ttft_cold = await one_prefill(
                np.concatenate(
                    [sys_ids, rng.integers(0, VOCAB_EFF, size=(TAIL,))]
                )
            )
            # untimed warm-up: first warm session compiles the short
            # suffix-prefill bucket
            await one_prefill(
                np.concatenate(
                    [sys_ids, rng.integers(0, VOCAB_EFF, size=(TAIL,))]
                )
            )
            warm = [
                await one_prefill(
                    np.concatenate(
                        [sys_ids, rng.integers(0, VOCAB_EFF, size=(TAIL,))]
                    )
                )
                for _ in range(N_WARM)
            ]
            ttft_warm = float(np.mean(warm))
            stats = server.manager.prefix_stats()
            # hit rate over the sessions that COULD hit (all but the
            # bucket-warmer and the cold run)
            hit_rate = stats["prefix_hits"] / max(N_WARM + 1, 1)
            RESULTS["prefix_cache"] = {
                "ttft_cold_ms": ttft_cold,
                "ttft_warm_ms": ttft_warm,
                "speedup": ttft_cold / max(ttft_warm, 1e-9),
                "hit_tokens": stats["prefix_hit_tokens"],
                "hits": stats["prefix_hits"],
                "hit_rate": hit_rate,
                "cow_copies": stats["cow_copies"],
                "cached_pages": stats["prefix_cached_pages"],
            }
            phase("prefix_cache", "ok")
            log(
                f"prefix cache: cold ttft {ttft_cold:.1f} ms, warm "
                f"{ttft_warm:.1f} ms ({ttft_cold / max(ttft_warm, 1e-9):.2f}x), "
                f"hits {stats['prefix_hits']} "
                f"({stats['prefix_hit_tokens']} tokens), "
                f"cow {stats['cow_copies']}"
            )
        finally:
            for stop in (server.stop, reg.stop):
                try:
                    await asyncio.wait_for(stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    asyncio.run(run())


def run_interference(spec, params, smoke: bool) -> None:
    """Stall-free scheduling phase: N sessions in steady single-token
    decode while a LONG prompt prefills on the same server. Monolithic
    prefill head-of-line-blocks every decode step for the whole prompt;
    chunked prefill (--prefill-chunk) lets queued decode steps run between
    chunks, so decode TBT stays near its unloaded value. Reports decode
    TBT p50/p95 during the prefill for both modes plus the chunk/interleave
    counters that prove the schedule actually interleaved."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    LONG = 256 if smoke else 2048  # the neighbor's pasted document
    CHUNK = 64
    N_DEC = 3
    PROMPT = 2 * PAGE  # the decoders' own short prompts
    VOCAB_EFF = min(1024, spec.vocab_size)

    async def one_mode(
        chunk: int, mixed: bool = False, spec_batch: bool = False,
        spec_traffic=None, window_ms=None,
    ) -> dict:
        # spec_traffic: a bind(rc) -> async-generate callable for the
        # universal modes' concurrent speculative stream; window_ms
        # pins the gather window so the universal/baseline pair differ
        # ONLY in fusion scope
        old_window = os.environ.get(  # bbtpu: noqa[BB005]
            "BBTPU_BATCH_WINDOW_MS"
        )
        if window_ms is not None:
            os.environ["BBTPU_BATCH_WINDOW_MS"] = window_ms
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="bench_itf", start=0, end=span_layers, params=params,
            spec=spec, registry=rc(),
            num_pages=max(256, 2 * (LONG // PAGE) + 64), page_size=PAGE,
            max_batch=N_DEC + 1, prefill_chunk=chunk, mixed_batch=mixed,
            spec_batch=spec_batch,
        )
        await server.start()
        gen_spec = spec_traffic(rc) if spec_traffic else None
        manager = RemoteSequenceManager(rc(), "bench_itf", span_layers)
        rng = np.random.default_rng(13)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)

        async def one_token(s):
            nid = rng.integers(0, VOCAB_EFF, size=(1, 1))
            await s.step(embed_table[nid], ids=nid)

        async def long_prefill_once() -> float:
            ids = rng.integers(0, VOCAB_EFF, size=(1, LONG))
            s = InferenceSession(manager, max_length=LONG + 4, batch_size=1)
            async with s:
                t0 = time.perf_counter()
                await s.step(embed_table[ids], ids=ids)
                return (time.perf_counter() - t0) * 1000.0

        decs = []
        try:
            # untimed warm pass: compile the long-prompt (or per-chunk)
            # prefill buckets off the measured path
            await long_prefill_once()
            for _ in range(N_DEC):
                s = InferenceSession(
                    manager, max_length=PROMPT + 64, batch_size=1
                )
                await s.__aenter__()
                decs.append(s)
                ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                await s.step(embed_table[ids], ids=ids)
                await one_token(s)  # compile the solo decode bucket
            for _ in range(2):
                # concurrent warm rounds: compile the BATCHED decode
                # widths (2..N_DEC) off the measured path, else the first
                # coalesced step mid-prefill pays a compile and pollutes
                # the TBT percentiles
                await asyncio.gather(*(one_token(s) for s in decs))

            if gen_spec is not None:
                # compile the drafter + tree-verify buckets off the
                # measured path, exactly like the decode warm rounds
                await gen_spec()

            gaps: list[float] = []
            prefill_done = asyncio.Event()
            spec_rounds = 0

            async def decode_loop(s):
                # keep decoding while the long prefill is in flight; a
                # step caught mid-prefill still records its full stall
                while not prefill_done.is_set():
                    t0 = time.perf_counter()
                    await one_token(s)
                    gaps.append((time.perf_counter() - t0) * 1000.0)

            async def spec_loop():
                # concurrent speculative stream: at least one full
                # generation (smoke prefills can finish before a round
                # does), then keep speculating until the prefill lands
                nonlocal spec_rounds
                while True:
                    await gen_spec()
                    spec_rounds += 1
                    if prefill_done.is_set():
                        break

            async def measured_prefill():
                try:
                    return await long_prefill_once()
                finally:
                    prefill_done.set()

            results = await asyncio.gather(
                measured_prefill(), *(decode_loop(s) for s in decs),
                *([spec_loop()] if gen_spec is not None else []),
            )
            ttft_ms = results[0]
            waits = server.compute.wait_stats_ms()
            xs = sorted(gaps)

            def pct(p):
                return xs[min(len(xs) - 1, round(p * (len(xs) - 1)))]

            return {
                "tbt_p50_ms": pct(0.50) if xs else 0.0,
                "tbt_p95_ms": pct(0.95) if xs else 0.0,
                "decode_steps": len(gaps),
                "ttft_ms": ttft_ms,
                "prefill_chunks": server.prefill_chunks,
                "decode_steps_interleaved": server.decode_steps_interleaved,
                "decode_wait_p95_ms": waits["decode"]["p95"],
                "dispatches_per_token": (
                    server.step_dispatches / max(server.step_tokens, 1)
                ),
                "mixed_dispatches": server.mixed_dispatches,
                "mixed_tokens": server.mixed_tokens,
                "tree_group_dispatches": server.tree_group_dispatches,
                "ragged_group_dispatches": server.ragged_group_dispatches,
                "ragged_cross_kind_dispatches": (
                    server.ragged_cross_kind_dispatches
                ),
                "spec_rounds": spec_rounds,
            }
        finally:
            if window_ms is not None:
                if old_window is None:
                    os.environ.pop(  # bbtpu: noqa[BB005]
                        "BBTPU_BATCH_WINDOW_MS", None
                    )
                else:
                    os.environ[  # bbtpu: noqa[BB005]
                        "BBTPU_BATCH_WINDOW_MS"
                    ] = old_window
            for s in decs:
                try:
                    await s.__aexit__(None, None, None)
                except Exception:  # noqa: BLE001
                    pass
            for stop in (server.stop, reg.stop):
                try:
                    await asyncio.wait_for(stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    def make_spec_binder():
        # client head + self-drafter for the universal modes' concurrent
        # speculative stream (run_spec_decode idiom, sized to VOCAB_EFF)
        import jax.numpy as jnp

        from bloombee_tpu.client.model import DistributedModelForCausalLM
        from bloombee_tpu.client.speculative import generate_speculative
        from bloombee_tpu.spec.drafter import (
            GreedyTreeDrafter,
            LocalJaxDraftModel,
        )
        from bloombee_tpu.utils.tree import unstack_params

        srng = np.random.default_rng(41)
        client_params = {
            "embed": jnp.asarray(
                srng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02,
                jnp.float32,
            ),
            "norm": jnp.ones((spec.hidden_size,), jnp.float32),
            "lm_head": jnp.asarray(
                srng.standard_normal((spec.hidden_size, VOCAB_EFF)) * 0.02,
                jnp.float32,
            ),
        }
        draft_model = LocalJaxDraftModel(
            spec, unstack_params(params, span_layers), client_params
        )
        prompt = srng.integers(0, VOCAB_EFF, size=(1, 8))
        n_new = 4 if smoke else 8

        def bind(rc):
            model = DistributedModelForCausalLM(
                spec, client_params,
                RemoteSequenceManager(rc(), "bench_itf", span_layers),
            )

            async def gen():
                await generate_speculative(
                    model,
                    GreedyTreeDrafter(draft_model, branching=(2, 1)),
                    prompt, max_new_tokens=n_new,
                )

            return gen

        return bind

    chunked = asyncio.run(one_mode(CHUNK))
    mono = asyncio.run(one_mode(0))
    # third mode: chunked prefill + mixed-batch dispatch (ISSUE 8) — the
    # waiting decode steps ride inside the prefill chunk's dispatch, so
    # dispatches_per_token drops below the interleaved-but-separate value
    mixed = asyncio.run(one_mode(CHUNK, mixed=True))
    # universal mode (ISSUE 17): the SAME contended scenario plus a
    # concurrent speculative-decode stream — first mixed-only (the PR-8
    # baseline: tree-verify rounds dispatch solo next to the fused
    # decode+chunk steps), then with the universal ragged path (decode +
    # tree + chunk rows share ONE device step). Identical traffic and
    # gather window; only the fusion scope differs, so the
    # dispatches_per_token delta isolates the unified dispatch
    spec_binder = make_spec_binder()
    uni_base = asyncio.run(one_mode(
        CHUNK, mixed=True, spec_traffic=spec_binder, window_ms="8",
    ))
    universal = asyncio.run(one_mode(
        CHUNK, mixed=True, spec_batch=True, spec_traffic=spec_binder,
        window_ms="8",
    ))
    RESULTS["interference"] = {
        "chunked": chunked,
        "monolithic": mono,
        "mixed": mixed,
        "universal_baseline": uni_base,
        "universal": universal,
        "chunk": CHUNK,
        "long_tokens": LONG,
        "tbt_p95_speedup": (
            mono["tbt_p95_ms"] / max(chunked["tbt_p95_ms"], 1e-9)
        ),
        "dispatches_per_token_reduction": (
            chunked["dispatches_per_token"]
            / max(mixed["dispatches_per_token"], 1e-9)
        ),
        "universal_dispatches_per_token_reduction": (
            uni_base["dispatches_per_token"]
            / max(universal["dispatches_per_token"], 1e-9)
        ),
    }
    phase("interference", "ok")
    log(
        f"interference ({N_DEC} decoders vs {LONG}-token prefill): chunked "
        f"TBT p50 {chunked['tbt_p50_ms']:.1f} / p95 "
        f"{chunked['tbt_p95_ms']:.1f} ms over {chunked['decode_steps']} "
        f"steps ({chunked['prefill_chunks']} chunks, "
        f"{chunked['decode_steps_interleaved']} interleaved) vs monolithic "
        f"p50 {mono['tbt_p50_ms']:.1f} / p95 {mono['tbt_p95_ms']:.1f} ms "
        f"over {mono['decode_steps']} steps; chunked prefill ttft "
        f"{chunked['ttft_ms']:.0f} ms vs {mono['ttft_ms']:.0f} ms"
    )
    log(
        f"mixed-batch dispatch: {mixed['dispatches_per_token']:.4f} "
        f"dispatches/token ({mixed['mixed_dispatches']} fused dispatches, "
        f"{mixed['mixed_tokens']} tokens) vs chunked "
        f"{chunked['dispatches_per_token']:.4f} — "
        f"{RESULTS['interference']['dispatches_per_token_reduction']:.2f}x "
        f"fewer; mixed TBT p95 {mixed['tbt_p95_ms']:.1f} ms"
    )
    log(
        f"universal ragged dispatch (+spec stream, {universal['spec_rounds']}"
        f" rounds): {universal['dispatches_per_token']:.4f} dispatches/token"
        f" ({universal['ragged_cross_kind_dispatches']} cross-kind of "
        f"{universal['ragged_group_dispatches']} ragged dispatches) vs "
        f"mixed-only {uni_base['dispatches_per_token']:.4f} — "
        f"{RESULTS['interference']['universal_dispatches_per_token_reduction']:.2f}x "
        f"fewer; universal TBT p95 {universal['tbt_p95_ms']:.1f} ms vs "
        f"{uni_base['tbt_p95_ms']:.1f} ms"
    )


def run_spec_decode(spec, params, smoke: bool) -> None:
    """Speculative-decode phase: N sessions speculate concurrently against
    one server, each round shipping a drafted token tree for verification.
    Solo mode (flag off) pays one device dispatch per session per round;
    --spec-batch gathers concurrent rounds sharing (layers, adapter, dtype)
    into ONE grouped ragged dispatch. The drafter runs the SAME weights as
    the server (client-side, unstacked), so acceptance is high and the
    dispatch counters — not token quality — are what the modes contrast."""
    import asyncio

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.utils.tree import unstack_params

    span_layers = spec.num_hidden_layers
    N_SESS = 2
    N_NEW = 6 if smoke else 16
    PROMPT = 8
    VOCAB_EFF = min(1024, spec.vocab_size)

    rng = np.random.default_rng(41)
    # client head sized to the effective vocab: every generated id comes
    # from an argmax over these logits, so embeds never index past it
    client_params = {
        # jnp (not np): the drafter jit-traces embeds, and a numpy table
        # indexed by a tracer raises TracerArrayConversionError
        "embed": jnp.asarray(
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02,
            jnp.float32,
        ),
        "norm": jnp.ones((spec.hidden_size,), jnp.float32),
        "lm_head": jnp.asarray(
            rng.standard_normal((spec.hidden_size, VOCAB_EFF)) * 0.02,
            jnp.float32,
        ),
    }
    draft_model = LocalJaxDraftModel(
        spec, unstack_params(params, span_layers), client_params
    )
    prompts = [
        rng.integers(0, VOCAB_EFF, size=(1, PROMPT)) for _ in range(N_SESS)
    ]

    async def one_mode(spec_batch: bool, window_ms: str) -> dict:
        # save/restore needs the raw possibly-absent value, not the
        # typed default env.get would substitute
        old = os.environ.get("BBTPU_BATCH_WINDOW_MS")  # bbtpu: noqa[BB005]
        os.environ["BBTPU_BATCH_WINDOW_MS"] = window_ms
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="bench_spec", start=0, end=span_layers,
            params=params, spec=spec, registry=rc(), num_pages=256,
            page_size=16, max_batch=2 * N_SESS, spec_batch=spec_batch,
        )
        await server.start()
        model = DistributedModelForCausalLM(
            spec, client_params,
            RemoteSequenceManager(rc(), "bench_spec", span_layers),
        )
        try:
            coros = [
                generate_speculative(
                    model,
                    GreedyTreeDrafter(draft_model, branching=(2, 1)),
                    p, max_new_tokens=N_NEW,
                )
                for p in prompts
            ]
            t0 = time.perf_counter()
            if spec_batch:
                outs = await asyncio.gather(*coros)
            else:
                outs = [await c for c in coros]
            wall_s = time.perf_counter() - t0
            tokens = N_SESS * N_NEW
            return {
                "tokens": [np.asarray(o).tolist() for o in outs],
                "wall_s": wall_s,
                "tok_per_s": tokens / max(wall_s, 1e-9),
                "tree_steps": server.tree_steps,
                "tree_group_dispatches": server.tree_group_dispatches,
                "mean_tree_batch_width": (
                    server.tree_group_members
                    / max(server.tree_group_dispatches, 1)
                ),
                "spec_tokens_drafted": server.spec_tokens_drafted,
                "spec_tokens_accepted": server.spec_tokens_accepted,
                "step_dispatches": server.step_dispatches,
                "dispatches_per_token": (
                    server.step_dispatches / max(tokens, 1)
                ),
            }
        finally:
            if old is None:
                os.environ.pop("BBTPU_BATCH_WINDOW_MS", None)
            else:
                os.environ["BBTPU_BATCH_WINDOW_MS"] = old
            for stop in (server.stop, reg.stop):
                try:
                    await asyncio.wait_for(stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    # window must exceed per-round client think time (drafter forward) or
    # concurrently pacing sessions phase-lock and never share a window
    batched = asyncio.run(one_mode(True, "2000"))
    solo = asyncio.run(one_mode(False, "0"))
    identical = batched["tokens"] == solo["tokens"]
    reduction = solo["dispatches_per_token"] / max(
        batched["dispatches_per_token"], 1e-9
    )
    for mode in (batched, solo):
        mode.pop("tokens")  # raw ids would bloat the ledger
    RESULTS["spec_decode"] = {
        "batched": batched,
        "solo": solo,
        "sessions": N_SESS,
        "new_tokens_per_session": N_NEW,
        "token_identical": identical,
        "dispatches_per_token_reduction": reduction,
    }
    phase("spec_decode", "ok" if identical else "failed: tokens diverged")
    log(
        f"spec_decode ({N_SESS} sessions x {N_NEW} tokens): batched "
        f"{batched['dispatches_per_token']:.3f} dispatches/token "
        f"({batched['tree_group_dispatches']} group dispatches, width "
        f"{batched['mean_tree_batch_width']:.2f}) vs solo "
        f"{solo['dispatches_per_token']:.3f} — {reduction:.2f}x fewer; "
        f"token_identical={identical}"
    )


def run_overload(spec, params, smoke: bool) -> None:
    """Overload phase: more client demand than capacity. Two same-span
    servers; N light sessions in steady single-token decode (established
    streams) while a heavy client floods NEW prefill sessions at many
    times the light rate. Protected mode (admission control + load-aware
    routing) must shed the heavy client's new work with retriable
    `overloaded(retry_after_ms)` — zero hard session failures — while the
    light sessions' decode TBT stays bounded; unprotected mode lets the
    flood queue behind everyone. Reports light TBT p50/p95, hard failures,
    sheds, and the light client's throughput share for both modes."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import (
        MissingBlocksError,
        RemoteSequenceManager,
    )
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.wire.rpc import OverloadedError

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT = 2 * PAGE  # light sessions' own prompts
    HEAVY = 128 if smoke else 512  # the flood's per-session prefill
    N_LIGHT = 2
    N_HEAVY = 4  # concurrent heavy open->prefill->close loops
    DURATION = 5.0 if smoke else 10.0
    ADMIT_HIGH = 75.0 if smoke else 250.0
    VOCAB_EFF = min(1024, spec.vocab_size)

    async def one_mode(protected: bool) -> dict:
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = []
        for _ in range(2):
            srv = BlockServer(
                model_uid="bench_ovl", start=0, end=span_layers,
                params=params, spec=spec, registry=rc(),
                num_pages=max(256, 4 * (HEAVY // PAGE) + 64),
                page_size=PAGE, max_batch=N_LIGHT,
                admit=protected, admit_high_ms=ADMIT_HIGH,
                load_advert_s=0.5 if protected else 0.0,
            )
            await srv.start()
            servers.append(srv)

        def mk_manager():
            return RemoteSequenceManager(
                rc(), "bench_ovl", span_layers,
                load_aware=protected, update_period=1.0,
            )

        rng = np.random.default_rng(17)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)

        light_mgr, heavy_mgr = mk_manager(), mk_manager()
        gaps: list[float] = []
        counts = {
            "light_tokens": 0, "heavy_tokens": 0,
            "sheds": 0, "hard_failures": 0, "heavy_completed": 0,
        }
        lights = []
        stop = asyncio.Event()

        async def one_token(s):
            nid = rng.integers(0, VOCAB_EFF, size=(1, 1))
            await s.step(embed_table[nid], ids=nid)

        async def light_loop(s):
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    await one_token(s)
                except OverloadedError:
                    # established streams must never be shed; count it as
                    # a hard failure so the acceptance gate catches it
                    counts["hard_failures"] += 1
                    return
                except Exception:  # noqa: BLE001
                    counts["hard_failures"] += 1
                    return
                gaps.append((time.perf_counter() - t0) * 1000.0)
                counts["light_tokens"] += 1

        async def heavy_loop():
            # the flood: open a NEW session, prefill, close, repeat —
            # overload_retries=0 so the first shed surfaces (and counts)
            # instead of being retried away inside the session
            while not stop.is_set():
                ids = rng.integers(0, VOCAB_EFF, size=(1, HEAVY))
                s = InferenceSession(
                    heavy_mgr, max_length=HEAVY + 4, batch_size=1,
                    client_id="bench-heavy", overload_retries=0,
                )
                try:
                    async with s:
                        await s.step(embed_table[ids], ids=ids)
                    counts["heavy_tokens"] += HEAVY
                    counts["heavy_completed"] += 1
                except OverloadedError as e:
                    counts["sheds"] += 1
                    retry = min((e.retry_after_ms or 250) / 1000.0, 2.0)
                    await asyncio.sleep(retry)
                except MissingBlocksError:
                    # every server is inside its overload backoff: the
                    # swarm told this client to go away and it has nowhere
                    # to reroute — that is backpressure working, not a
                    # failure; wait out the (short) penalty
                    counts["sheds"] += 1
                    await asyncio.sleep(0.25)
                except Exception:  # noqa: BLE001
                    counts["hard_failures"] += 1
                    await asyncio.sleep(0.2)

        try:
            # establish the light sessions (and compile every bucket)
            # BEFORE the flood starts: their later decode steps are
            # in-flight work the admission controller always admits
            for _ in range(N_LIGHT):
                s = InferenceSession(
                    light_mgr, max_length=PROMPT + 2048, batch_size=1,
                    client_id="bench-light",
                )
                await s.__aenter__()
                lights.append(s)
                ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                await s.step(embed_table[ids], ids=ids)
                await one_token(s)
            # compile the heavy prefill bucket off the measured path
            warm = rng.integers(0, VOCAB_EFF, size=(1, HEAVY))
            ws = InferenceSession(
                heavy_mgr, max_length=HEAVY + 4, batch_size=1,
                client_id="bench-heavy",
            )
            async with ws:
                await ws.step(embed_table[warm], ids=warm)

            async def timer():
                await asyncio.sleep(DURATION)
                stop.set()

            await asyncio.gather(
                timer(),
                *(light_loop(s) for s in lights),
                *(heavy_loop() for _ in range(N_HEAVY)),
            )
            xs = sorted(gaps)

            def pct(p):
                return xs[min(len(xs) - 1, round(p * (len(xs) - 1)))]

            total = counts["light_tokens"] + counts["heavy_tokens"]
            shed_stats = [
                srv.admission.stats() for srv in servers if srv.admission
            ]
            return {
                "tbt_p50_ms": pct(0.50) if xs else 0.0,
                "tbt_p95_ms": pct(0.95) if xs else 0.0,
                "light_tokens": counts["light_tokens"],
                "heavy_tokens": counts["heavy_tokens"],
                "heavy_completed": counts["heavy_completed"],
                # decode steps vs fair step share: the light client pays
                # one queue slot per token just like each heavy prefill
                # pays one per chunk, so token share understates it; report
                # raw share for the ledger and let the gate compare modes
                "light_share": (
                    counts["light_tokens"] / total if total else 0.0
                ),
                "sheds": counts["sheds"],
                "hard_failures": counts["hard_failures"],
                "server_shed_requests": sum(
                    st["shed_requests"] for st in shed_stats
                ),
            }
        finally:
            for s in lights:
                try:
                    await s.__aexit__(None, None, None)
                except Exception:  # noqa: BLE001
                    pass
            for stopper in [srv.stop for srv in servers] + [reg.stop]:
                try:
                    await asyncio.wait_for(stopper(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    protected = asyncio.run(one_mode(True))
    unprotected = asyncio.run(one_mode(False))
    RESULTS["overload"] = {
        "protected": protected,
        "unprotected": unprotected,
        "heavy_prefill_tokens": HEAVY,
        "admit_high_ms": ADMIT_HIGH,
    }
    phase("overload", "ok")
    log(
        f"overload ({N_LIGHT} light decoders vs {N_HEAVY}x{HEAVY}-token "
        f"prefill flood): protected TBT p50 {protected['tbt_p50_ms']:.1f} / "
        f"p95 {protected['tbt_p95_ms']:.1f} ms, "
        f"{protected['sheds']} sheds, "
        f"{protected['hard_failures']} hard failures, light share "
        f"{protected['light_share']:.3f} vs unprotected p50 "
        f"{unprotected['tbt_p50_ms']:.1f} / p95 "
        f"{unprotected['tbt_p95_ms']:.1f} ms, "
        f"{unprotected['hard_failures']} hard failures, light share "
        f"{unprotected['light_share']:.3f}"
    )


def run_autoscale(spec, params, smoke: bool) -> None:
    """Elastic self-healing phase. Two legs:

    1. TBT leg: one primary + one warm standby on the same span; N light
       sessions decode steadily while heavy prefill sessions flood in (a
       shifting hot prompt). With the control loop ON (fast watermarks)
       the primary's load advert trips promotion, the standby starts
       serving, and load-aware heavy routing drains the primary's queue
       — light decode TBT p95 must beat the loop-OFF run (identical
       topology, watermark parked at infinity, so ONLY the control loop
       differs).
    2. Kill-recovery leg: greedy generation through the primary, killed
       after exactly half the tokens are out (deterministic relative to
       progress, not wall clock). The client rides the dark window
       (MissingBlocksError is retriable while the swarm heals), the
       standby promotes on span loss, and the resumed run's tokens must
       equal an uninterrupted reference exactly — zero hard session
       failures."""
    import asyncio

    import jax as _jax
    import jax.numpy as _jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT = 2 * PAGE
    HEAVY = 96 if smoke else 384  # the shifting hot prompts
    N_LIGHT = 2
    N_HEAVY = 3
    HEAVY_DEC = 8  # hot sessions decode too: they compete for the
    # batcher's max_batch decode seats, which is exactly the queueing
    # pressure promotion relieves (prefill alone rides the mixed
    # dispatch chunk lane and would never crowd the lights)
    DURATION = 5.0 if smoke else 10.0
    # unmeasured lead-in: in elastic mode the promotion fires here and the
    # freshly-promoted standby pays its jit-compile for the heavy prefill
    # bucket OUTSIDE the measured window — otherwise the one-off compile
    # transient dominates p95 and the comparison measures XLA, not the
    # control loop
    WARMUP = 4.0 if smoke else 8.0
    SETTLE = 3.0
    # a light session lives the WHOLE run (its decode budget covers
    # warmup + settle + the measured window): renewal mid-window would
    # re-route the light and muddy whose queue its gaps measure
    LIGHT_BUDGET = 1000 if smoke else 2048
    VOCAB_EFF = min(1024, spec.vocab_size)

    def _server(rc, *, standby=False, elastic=True, uid="bench_as",
                artifact_dir=None):
        kw = {}
        if artifact_dir:
            kw["artifact_dir"] = artifact_dir
        if standby:
            kw |= {
                "standby": True,
                # OFF mode parks the high watermark at infinity: the
                # standby stays warm but the control loop never fires,
                # so the two modes differ ONLY in the loop
                "promote_high_ms": 150.0 if elastic else 1e12,
                "promote_low_ms": 30.0,
                "promote_sustain_s": 0.5,
                "promote_jitter_s": 0.2,
            }
        return BlockServer(
            model_uid=uid, start=0, end=span_layers, params=params,
            spec=spec, registry=rc,
            num_pages=max(
                256,
                (
                    N_LIGHT * (PROMPT + LIGHT_BUDGET)
                    + (N_HEAVY + 1) * (HEAVY + HEAVY_DEC + 4)
                ) // PAGE + 48,
            ),
            page_size=PAGE,
            max_batch=N_LIGHT, announce_period=0.3, load_advert_s=0.25,
            **kw,
        )

    async def tbt_mode(elastic: bool) -> dict:
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        primary = _server(rc(), elastic=elastic)
        standby = _server(rc(), standby=True, elastic=elastic)
        await primary.start()
        await standby.start()

        def mk_manager():
            return RemoteSequenceManager(
                rc(), "bench_as", span_layers,
                load_aware=True, update_period=0.5,
            )

        rng = np.random.default_rng(23)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)
        light_mgr, heavy_mgr = mk_manager(), mk_manager()
        gaps: list[float] = []
        counts = {"hard_failures": 0, "heavy_completed": 0}
        stop = asyncio.Event()
        measuring = asyncio.Event()

        async def one_token(s):
            nid = rng.integers(0, VOCAB_EFF, size=(1, 1))
            await s.step(embed_table[nid], ids=nid)

        async def light_loop():
            while not stop.is_set():
                s = InferenceSession(
                    light_mgr, max_length=PROMPT + LIGHT_BUDGET + 4,
                    batch_size=1, client_id="bench-autoscale-light",
                )
                try:
                    async with s:
                        ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                        await s.step(embed_table[ids], ids=ids)
                        for _ in range(LIGHT_BUDGET):
                            if stop.is_set():
                                return
                            t0 = time.perf_counter()
                            await one_token(s)
                            if measuring.is_set():
                                gaps.append(
                                    (time.perf_counter() - t0) * 1000.0
                                )
                except Exception:  # noqa: BLE001
                    counts["hard_failures"] += 1
                    await asyncio.sleep(0.2)

        async def heavy_loop():
            while not stop.is_set():
                ids = rng.integers(0, VOCAB_EFF, size=(1, HEAVY))
                s = InferenceSession(
                    heavy_mgr, max_length=HEAVY + HEAVY_DEC + 4,
                    batch_size=1, client_id="bench-autoscale-heavy",
                )
                try:
                    async with s:
                        await s.step(embed_table[ids], ids=ids)
                        for _ in range(HEAVY_DEC):
                            if stop.is_set():
                                break
                            await one_token(s)
                    if measuring.is_set():
                        counts["heavy_completed"] += 1
                except Exception:  # noqa: BLE001
                    counts["hard_failures"] += 1
                    await asyncio.sleep(0.2)

        try:
            # compile the heavy prefill bucket on the primary up front so
            # the first flood wave is not a compile wave
            warm = rng.integers(0, VOCAB_EFF, size=(1, HEAVY))
            ws = InferenceSession(
                heavy_mgr, max_length=HEAVY + 4, batch_size=1
            )
            async with ws:
                await ws.step(embed_table[warm], ids=warm)

            async def timer():
                await asyncio.sleep(WARMUP)
                if elastic:
                    # the promotion should have fired during warmup; give
                    # it a bounded grace, then let the promoted standby
                    # absorb its compile transient before measuring
                    deadline = time.monotonic() + 15.0
                    while (
                        not standby._promoted
                        and time.monotonic() < deadline
                    ):
                        await asyncio.sleep(0.2)
                await asyncio.sleep(SETTLE)
                measuring.set()
                await asyncio.sleep(DURATION)
                stop.set()

            await asyncio.gather(
                timer(),
                *(light_loop() for _ in range(N_LIGHT)),
                *(heavy_loop() for _ in range(N_HEAVY)),
            )
            xs = sorted(gaps)

            def pct(p):
                return xs[min(len(xs) - 1, round(p * (len(xs) - 1)))]

            return {
                "tbt_p50_ms": pct(0.50) if xs else 0.0,
                "tbt_p95_ms": pct(0.95) if xs else 0.0,
                "decode_steps": len(gaps),
                "heavy_completed": counts["heavy_completed"],
                "hard_failures": counts["hard_failures"],
                "promotions": standby.promotions,
                "demotions": standby.demotions,
                "promoted_at_end": bool(standby._promoted),
            }
        finally:
            for stopper in (primary.stop, standby.stop, reg.stop):
                try:
                    await asyncio.wait_for(stopper(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    async def recovery_leg(preinstall: bool = False) -> dict:
        """Kill-recovery leg. With preinstall=True the primary writes a
        compile-artifact store, the standby pre-fetches it over the wire
        before the kill, and the promoted standby's first token is served
        from persistent-cache loads; the caller clears jax's in-memory jit
        cache at the promotion boundary either way, so both variants pay
        a fresh process's compile bill and promotion_to_first_token_ms
        isolates exactly what pre-install buys."""
        import shutil
        import tempfile

        from bloombee_tpu.server import artifacts as _artifacts

        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        art_a = art_b = None
        if preinstall:
            art_a = tempfile.mkdtemp(prefix="bbtpu-bench-art-src.")
            art_b = tempfile.mkdtemp(prefix="bbtpu-bench-art-dst.")

        keys = _jax.random.split(_jax.random.PRNGKey(29), 2)
        client_params = {
            "embed": _jax.random.normal(
                keys[0], (VOCAB_EFF, spec.hidden_size), _jnp.float32
            ) * 0.02,
            "norm": _jnp.ones((spec.hidden_size,), _jnp.float32),
            "lm_head": _jax.random.normal(
                keys[1], (spec.hidden_size, VOCAB_EFF), _jnp.float32
            ) * 0.02,
        }
        # construct the standby FIRST: BlockServer points the process-wide
        # persistent-cache config at its artifact dir, and the PRIMARY'S
        # store must be the one the live compiles land in
        standby = _server(rc(), standby=True, uid="bench_asr",
                          artifact_dir=art_b)
        primary = _server(rc(), uid="bench_asr", artifact_dir=art_a)
        await primary.start()
        await standby.start()
        if preinstall:
            # re-trace so this leg's compiles are real events that land in
            # the primary's store (earlier legs warmed the same shapes
            # in-memory, which persists nothing)
            _jax.clear_caches()
        rng = np.random.default_rng(31)
        prompt = rng.integers(0, VOCAB_EFF, size=(1, 8))
        K = 12 if smoke else 24

        def mk_model():
            m = DistributedModelForCausalLM(
                spec, client_params,
                RemoteSequenceManager(
                    rc(), "bench_asr", span_layers, update_period=0.5
                ),
            )
            # a generous retry budget: the dark window between primary
            # death and standby promotion is a couple seconds here, and
            # each retry attempt sleeps on its backoff schedule
            m.config.max_retries = 12
            return m

        try:
            ref = await mk_model().generate(
                prompt, max_new_tokens=K, server_decode=False
            )

            # the kill lands after EXACTLY K//2 tokens — deterministic
            # relative to generation progress, so the dark window always
            # falls mid-flight (a wall-clock killer can miss a fast run
            # entirely and trivially pass)
            K1 = K // 2
            m = mk_model()
            sess = m.inference_session(
                max_length=prompt.shape[1] + K + 2, batch_size=1
            )
            hard_failures = 0
            got = None
            stall_ms = 0.0
            first_token_ms = 0.0
            try:
                async with sess:
                    ids1 = await m.generate(
                        prompt, max_new_tokens=K1, session=sess,
                        server_decode=False,
                    )
                    if preinstall:
                        await standby.prefetch_artifacts()
                    await primary.stop()
                    # both variants pay a fresh process's compile bill at
                    # the promotion boundary; the preinstall variant gets
                    # to pay it with persistent-cache loads
                    _jax.clear_caches()
                    if preinstall:
                        _artifacts.enable_persistent_cache(art_b)
                    t0 = time.time()
                    ids2 = await m.generate(
                        ids1[:, -1:], max_new_tokens=1, session=sess,
                        server_decode=False,
                    )
                    first_token_ms = (time.time() - t0) * 1000.0
                    ids3 = await m.generate(
                        ids2[:, -1:], max_new_tokens=K - K1 - 1,
                        session=sess, server_decode=False,
                    )
                    stall_ms = (time.time() - t0) * 1000.0
                got = np.concatenate(
                    [np.asarray(ids1), np.asarray(ids2)[:, 1:],
                     np.asarray(ids3)[:, 1:]], axis=1
                )
            except Exception as e:  # noqa: BLE001
                hard_failures = 1
                log(f"autoscale recovery generation FAILED: {e!r}")
            identical = got is not None and np.array_equal(
                got, np.asarray(ref)
            )
            return {
                "stall_ms": stall_ms,
                "first_token_ms": first_token_ms,
                "token_identical": identical,
                "hard_failures": hard_failures,
                "promotions": standby.promotions,
                "preinstalled": bool(standby._artifacts_preinstalled),
            }
        finally:
            for stopper in (standby.stop, reg.stop):
                try:
                    await asyncio.wait_for(stopper(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass
            for d in (art_a, art_b):
                if d:
                    shutil.rmtree(d, ignore_errors=True)

    elastic = asyncio.run(tbt_mode(True))
    static = asyncio.run(tbt_mode(False))
    # the preinstall leg repoints jax's process-wide persistent-cache
    # config at throwaway artifact dirs; later phases must not inherit it
    _cfg = {
        k: getattr(_jax.config, k)
        for k in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
            "jax_persistent_cache_enable_xla_caches",
        )
    }
    try:
        recovery = asyncio.run(recovery_leg(False))
        recovery_pre = asyncio.run(recovery_leg(True))
    finally:
        for k, v in _cfg.items():
            _jax.config.update(k, v)
        # the persistent-cache object latches the dir it initialized
        # with; re-latch against the restored config so later phases
        # don't write into the deleted artifact tmp dirs
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    RESULTS["autoscale"] = {
        "elastic": elastic,
        "static": static,
        "recovery": recovery,
        "recovery_preinstall": recovery_pre,
        "heavy_prefill_tokens": HEAVY,
        "tbt_p95_speedup": (
            static["tbt_p95_ms"] / max(elastic["tbt_p95_ms"], 1e-9)
        ),
    }
    ok = (
        recovery["token_identical"]
        and recovery["hard_failures"] == 0
        and recovery["promotions"] >= 1
        and elastic["promotions"] >= 1
        and recovery_pre["token_identical"]
        and recovery_pre["hard_failures"] == 0
    )
    phase("autoscale", "ok" if ok else "failed: see autoscale ledger")
    log(
        f"autoscale ({N_LIGHT} light decoders vs {N_HEAVY}x{HEAVY}-token "
        f"flood): elastic TBT p50 {elastic['tbt_p50_ms']:.1f} / p95 "
        f"{elastic['tbt_p95_ms']:.1f} ms "
        f"({elastic['promotions']} promotions, promoted_at_end="
        f"{elastic['promoted_at_end']}) vs static p50 "
        f"{static['tbt_p50_ms']:.1f} / p95 {static['tbt_p95_ms']:.1f} ms "
        f"— {RESULTS['autoscale']['tbt_p95_speedup']:.2f}x; recovery "
        f"stall {recovery['stall_ms']:.0f} ms, token_identical="
        f"{recovery['token_identical']}, hard_failures="
        f"{recovery['hard_failures']}; promotion-to-first-token "
        f"cold {recovery['first_token_ms']:.0f} ms vs pre-installed "
        f"{recovery_pre['first_token_ms']:.0f} ms (preinstalled="
        f"{recovery_pre['preinstalled']})"
    )


def run_failover(spec, params) -> None:
    """Fast-failover phase: two same-span servers; a session decodes with
    standby-KV replication, the primary dies mid-decode, and the client
    recovers onto the standby. With replication the recovery probe adopts
    the replicated pages and replays only the unsealed tail; without it
    the whole history re-prefills. Reports both stalls + replayed-token
    counts."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT, DECODE = 4 * PAGE, 24
    VOCAB_EFF = min(1024, spec.vocab_size)

    async def one_failover(repl_every: int) -> dict:
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(
                model_uid="bench_fo", start=0, end=span_layers,
                params=params, spec=spec, registry=rc(), num_pages=256,
                page_size=PAGE, max_batch=1, prefix_cache=True,
            )
            for _ in range(2)
        ]
        for srv in servers:
            await srv.start()
        manager = RemoteSequenceManager(rc(), "bench_fo", span_layers)
        rng = np.random.default_rng(11)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)

        async def one_token(s):
            nid = rng.integers(0, VOCAB_EFF, size=(1, 1))
            await s.step(embed_table[nid], ids=nid)

        try:
            s = InferenceSession(
                manager, max_length=PROMPT + DECODE + 4, batch_size=1,
                prefix_cache=True, repl_every=repl_every,
            )
            async with s:
                ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                await s.step(embed_table[ids], ids=ids)
                for _ in range(DECODE // 2):
                    await one_token(s)
                primary_port = s._spans[0].span.server_info.port
                primary = next(v for v in servers if v.port == primary_port)
                standby = next(v for v in servers if v.port != primary_port)
                if repl_every:
                    # let the async kv_put backlog land before the kill
                    for _ in range(200):
                        stats = standby.manager.prefix_stats()
                        if stats["repl_pages_installed"] >= (
                            (PROMPT + DECODE // 2) // PAGE
                        ):
                            break
                        await asyncio.sleep(0.05)
                await primary.stop()
                t0 = time.time()
                await one_token(s)  # hits the dead primary -> recovery
                stall_ms = (time.time() - t0) * 1000.0
                for _ in range(DECODE // 2 - 1):
                    await one_token(s)
                return {
                    "stall_ms": stall_ms,
                    "replayed": int(s.failover_replayed_tokens),
                }
        finally:
            for thing in (*servers, reg):
                try:
                    await asyncio.wait_for(thing.stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    repl = asyncio.run(one_failover(repl_every=1))
    full = asyncio.run(one_failover(repl_every=0))
    RESULTS["failover"] = {
        "stall_repl_ms": repl["stall_ms"],
        "stall_replay_ms": full["stall_ms"],
        "replayed_repl": repl["replayed"],
        "replayed_full": full["replayed"],
    }
    phase("failover", "ok")
    log(
        f"failover: stall {repl['stall_ms']:.1f} ms replaying "
        f"{repl['replayed']} tokens (replication on) vs "
        f"{full['stall_ms']:.1f} ms replaying {full['replayed']} tokens "
        f"(full replay)"
    )


def run_reconnect(spec, params) -> None:
    """Reconnect-resume phase: ONE server with session leases on; a session
    prefills and decodes half its budget, then its connection is severed
    (transport abort — the wire equivalent of a NAT timeout / partition
    heal). With resume on, the client re-attaches the lease-parked session
    on a fresh stream and retransmits the interrupted step under its
    original id (the server answers from its recorded reply if it already
    applied it) — zero prompt tokens replayed. With resume off, the client
    rebuilds a fresh session and replays the whole history. Reports both
    stalls, replayed-token counts, and the server's resume/dedup
    counters."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT, DECODE = 4 * PAGE, 24
    VOCAB_EFF = min(1024, spec.vocab_size)

    async def one_reconnect(resume: bool) -> dict:
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="bench_rec", start=0, end=span_layers, params=params,
            spec=spec, registry=rc(), num_pages=256, page_size=PAGE,
            max_batch=1, session_lease_s=30.0,
        )
        await server.start()
        manager = RemoteSequenceManager(rc(), "bench_rec", span_layers)
        rng = np.random.default_rng(19)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)

        async def one_token(s):
            nid = rng.integers(0, VOCAB_EFF, size=(1, 1))
            await s.step(embed_table[nid], ids=nid)

        try:
            s = InferenceSession(
                manager, max_length=PROMPT + DECODE + 4, batch_size=1,
                resume=resume,
            )
            async with s:
                ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                await s.step(embed_table[ids], ids=ids)
                for _ in range(DECODE // 2):
                    await one_token(s)
                # sever the wire under the session: every span conn dies
                # with no FIN handshake, like a partition healing into RST
                for sp in s._spans:
                    sp.conn.abort("bench: injected partition")
                t0 = time.time()
                await one_token(s)  # first post-partition step -> recovery
                stall_ms = (time.time() - t0) * 1000.0
                for _ in range(DECODE // 2 - 1):
                    await one_token(s)
                return {
                    "stall_ms": stall_ms,
                    "replayed": int(s.failover_replayed_tokens),
                    "resumed_streams": int(s.resumed_streams),
                    "steps_deduped": int(server.steps_deduped),
                    "sessions_resumed": int(server.sessions_resumed),
                }
        finally:
            for stop in (server.stop, reg.stop):
                try:
                    await asyncio.wait_for(stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    res = asyncio.run(one_reconnect(resume=True))
    full = asyncio.run(one_reconnect(resume=False))
    RESULTS["reconnect"] = {
        "stall_resume_ms": res["stall_ms"],
        "stall_replay_ms": full["stall_ms"],
        "replayed_resume": res["replayed"],
        "replayed_full": full["replayed"],
        "steps_deduped": res["steps_deduped"],
        "sessions_resumed": res["sessions_resumed"],
    }
    phase("reconnect", "ok")
    log(
        f"reconnect: stall {res['stall_ms']:.1f} ms replaying "
        f"{res['replayed']} tokens (resume: {res['sessions_resumed']} "
        f"resumed, {res['steps_deduped']} deduped) vs "
        f"{full['stall_ms']:.1f} ms replaying {full['replayed']} tokens "
        f"(full replay)"
    )


def run_wire(spec, params, smoke: bool) -> None:
    """Wire-path phase: decode through a real server under the chaos DELAY
    matrix's seeded wire jitter, three legs over the identical fault
    schedule — off-loop codec pipeline ON (default), pipeline OFF (the
    seed's synchronous scheduling), and a LEGACY peer (pre-negotiation
    server: sync codec, no advert, ignores ours). Reports bytes/token,
    codec ms/step, and decode-step p50/p95 per leg; all legs must be
    token-identical (the pipeline and the negotiation are scheduling and
    codec-choice changes, never numerics)."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.wire import faults
    from bloombee_tpu.wire.faults import FaultPlan, FaultRule
    from bloombee_tpu.wire.tensor_codec import (
        reset_transport_stats,
        transport_stats,
    )

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT = 2 * PAGE
    DECODE = 32 if smoke else 48
    VOCAB_EFF = min(1024, spec.vocab_size)
    # the chaos DELAY matrix's wire jitter, seeded so every leg replays
    # the SAME fault schedule: latency deltas are the pipeline's doing,
    # not the rng's
    DELAY_P, DELAY_S = 0.25, 0.004

    LEGS = (
        # key, pipeline_on, legacy_peer
        ("off", False, False),
        ("on", True, False),
        ("legacy", True, True),
    )

    async def run_legs() -> dict:
        """All three legs live in ONE event loop and decode in lockstep
        (one off/on/legacy step per round): scheduler, allocator, and GC
        noise land on every leg's samples alike instead of biasing
        whichever leg ran in the warmest stretch of the process. Each leg
        owns a FaultPlan seeded identically — and rng draws happen only
        on matching frames — so all legs replay the SAME delay schedule."""
        import gc

        # save/restore needs the raw possibly-absent value, not the
        # typed default env.get would substitute
        old_env = os.environ.get("BBTPU_WIRE_PIPELINE")  # bbtpu: noqa[BB005]
        rng = np.random.default_rng(31)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)
        ids0 = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
        legs: dict[str, dict] = {}
        try:
            for key, pipeline_on, legacy_peer in LEGS:
                # pipeline enablement is read at Connection construction:
                # flip the switch while this leg's swarm comes up so its
                # client AND accepted server conns get this leg's mode
                os.environ["BBTPU_WIRE_PIPELINE"] = (
                    "1" if pipeline_on else "0"
                )
                reg = RegistryServer(host="127.0.0.1")
                await reg.start()

                def rc(reg=reg):
                    return RegistryClient("127.0.0.1", reg.port)

                srv = BlockServer(
                    model_uid="bench_wire", start=0, end=span_layers,
                    params=params, spec=spec, registry=rc(), num_pages=256,
                    page_size=PAGE, max_batch=1,
                )
                await srv.start()
                if legacy_peer:
                    # accepted connections emulate a pre-negotiation
                    # build: codec work synchronous on the loop, no "cd"
                    # advert, ours ignored
                    srv.rpc.legacy_wire = True
                plan = FaultPlan(seed=29)
                plan.add(FaultRule(site="send", action="delay",
                                   method="sitem", prob=DELAY_P,
                                   delay_s=DELAY_S))
                manager = RemoteSequenceManager(
                    rc(), "bench_wire", span_layers
                )
                s = InferenceSession(
                    manager, max_length=PROMPT + DECODE + 8, batch_size=1,
                )
                await s.__aenter__()
                faults.set_plan(plan)
                out = await s.step(embed_table[ids0], ids=ids0)
                # one untimed decode step: the first decode-shaped call
                # pays the JAX trace/compile once per process, which
                # would otherwise swamp a short leg's p95
                logits = embed_table @ np.asarray(out, np.float32)[0, -1]
                nid = np.array([[int(np.argmax(logits))]])
                out = await s.step(embed_table[nid], ids=nid)
                faults.set_plan(None)
                legs[key] = {
                    "reg": reg, "srv": srv, "s": s, "plan": plan,
                    "out": out, "tokens": [int(nid[0, 0])],
                    "step_ms": [], "wire_bytes": 0.0, "raw_bytes": 0.0,
                    "codec_s": 0.0,
                }
            reset_transport_stats()
            prev = transport_stats()
            gc.collect()
            gc_was_enabled = gc.isenabled()
            gc.disable()
            try:
                for _ in range(DECODE):
                    for key, _, _ in LEGS:
                        leg = legs[key]
                        # pseudo-head: deterministic greedy selection so
                        # token-identity across legs is meaningful
                        logits = embed_table @ np.asarray(
                            leg["out"], dtype=np.float32
                        )[0, -1]
                        nid = np.array([[int(np.argmax(logits))]])
                        leg["tokens"].append(int(nid[0, 0]))
                        faults.set_plan(leg["plan"])
                        t0 = time.time()
                        leg["out"] = await leg["s"].step(
                            embed_table[nid], ids=nid
                        )
                        leg["step_ms"].append((time.time() - t0) * 1000.0)
                        faults.set_plan(None)
                        # transport counters are process-global; steps run
                        # strictly sequentially, so the per-step delta is
                        # this leg's traffic (both directions: every
                        # payload byte records once at serialize)
                        st = transport_stats()
                        leg["wire_bytes"] += (
                            st["tx"]["wire_bytes"] - prev["tx"]["wire_bytes"]
                        )
                        leg["raw_bytes"] += (
                            st["tx"]["raw_bytes"] - prev["tx"]["raw_bytes"]
                        )
                        leg["codec_s"] += (
                            st["tx"]["s"] + st["rx"]["s"]
                            - prev["tx"]["s"] - prev["rx"]["s"]
                        )
                        prev = st
            finally:
                if gc_was_enabled:
                    gc.enable()
            for key, _, _ in LEGS:
                legs[key]["pipe"] = legs[key]["srv"].rpc.pipeline_stats()
        finally:
            faults.set_plan(None)
            if old_env is None:
                os.environ.pop("BBTPU_WIRE_PIPELINE", None)
            else:
                os.environ["BBTPU_WIRE_PIPELINE"] = old_env
            for leg in legs.values():
                try:
                    await leg["s"].__aexit__(None, None, None)
                except Exception:  # noqa: BLE001
                    pass
                for thing in (leg["srv"], leg["reg"]):
                    try:
                        await asyncio.wait_for(thing.stop(), timeout=30.0)
                    except Exception:  # noqa: BLE001
                        pass

        out = {}
        for key, _, _ in LEGS:
            leg = legs[key]
            arr = np.asarray(leg["step_ms"])
            out[key] = {
                "tokens": leg["tokens"],
                "p50_ms": float(np.percentile(arr, 50)),
                "p95_ms": float(np.percentile(arr, 95)),
                "bytes_per_token": leg["wire_bytes"] / DECODE,
                "raw_bytes_per_token": leg["raw_bytes"] / DECODE,
                "codec_ms_per_step": leg["codec_s"] * 1000.0 / DECODE,
                "server_pipeline": leg["pipe"],
            }
        return out

    all_legs = asyncio.run(run_legs())
    on, off, legacy = all_legs["on"], all_legs["off"], all_legs["legacy"]
    token_identical = on["tokens"] == off["tokens"]
    token_identical_legacy = on["tokens"] == legacy["tokens"]
    RESULTS["wire"] = {
        "delay_matrix": {"prob": DELAY_P, "delay_s": DELAY_S},
        "decode_steps": DECODE,
        "bytes_per_token": on["bytes_per_token"],
        "raw_bytes_per_token": on["raw_bytes_per_token"],
        "codec_ms_per_step": on["codec_ms_per_step"],
        "pipeline_on": {k: v for k, v in on.items() if k != "tokens"},
        "pipeline_off": {k: v for k, v in off.items() if k != "tokens"},
        "legacy_peer": {k: v for k, v in legacy.items() if k != "tokens"},
        "p95_on_le_off": bool(on["p95_ms"] <= off["p95_ms"]),
        "token_identical": token_identical,
        "token_identical_legacy": token_identical_legacy,
    }
    assert token_identical, (
        f"pipeline on/off diverged: {on['tokens']} vs {off['tokens']}"
    )
    assert token_identical_legacy, (
        f"legacy-peer leg diverged: {legacy['tokens']} vs {on['tokens']}"
    )
    phase("wire", "ok")
    log(
        f"wire: {on['bytes_per_token']:.0f} B/token "
        f"(raw {on['raw_bytes_per_token']:.0f}), codec "
        f"{on['codec_ms_per_step']:.3f} ms/step; decode p95 "
        f"{on['p95_ms']:.1f} ms (pipeline on) vs {off['p95_ms']:.1f} ms "
        f"(off) vs {legacy['p95_ms']:.1f} ms (legacy peer) under "
        f"DELAY(p={DELAY_P}, {DELAY_S * 1000:.0f} ms); token-identical "
        f"across all legs"
    )


def run_swarm_sim() -> None:
    """Swarm-scale traffic simulation on the virtual clock: the REAL
    control plane (admission, promotion loop, measured rebalancing,
    Dijkstra routing with penalty classes) over the calibrated cost
    model, no device work at all. Always smoke-sized here — the bench
    wants the trend line, while `python -m bloombee_tpu.sim --require`
    owns the CI-scale blocking gate."""
    from bloombee_tpu.sim import SCENARIOS, run_scenario

    simr = RESULTS.setdefault("swarm_sim", {})
    for name in SCENARIOS:
        rep = run_scenario(name, sessions=200)
        m = rep["metrics"]
        simr[name] = {
            "sessions": m["sessions"],
            "completed": m["completed"],
            "shed_total": m["shed_total"],
            "retry_amplification": m["retry_amplification"],
            "shed_retry_amplification": m["shed_retry_amplification"],
            "shed_rate_converged_at_s": m["shed_rate_converged_at_s"],
            "promotions": m["promotions"],
            "rebalances_moved": m["rebalances_moved"],
            "gate_failures": rep["failures"],
            "wall_s": rep["wall_s"],
        }
        log(
            f"swarm_sim {name}: {m['completed']}/{m['sessions']} done, "
            f"amp {m['retry_amplification']:.2f}, "
            f"{len(rep['failures'])} gate failure(s), {rep['wall_s']}s"
        )
    phase("swarm_sim", "ok")


def run_integrity(spec, params, smoke: bool) -> None:
    """Byzantine-robustness phase: three whole-model replicas, one a LIAR
    (liar_p perturbs its span outputs before serialization — well-formed
    frames carrying wrong numbers). The client runs the integrity layer
    with audit_p=1.0: inline sanity gate + out_digest + cross-replica
    re-execution audits. Requirements: the liar is quarantined within the
    decode budget, the final generation is token-identical to a clean
    reference (every lie is caught BEFORE its token commits), and zero
    hard failures surface. Also reports the audit wall-clock overhead vs
    the same swarm with integrity off."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers
    PAGE = 16
    PROMPT = 2 * PAGE
    DECODE = 16 if smoke else 32
    VOCAB_EFF = min(1024, spec.vocab_size)
    LIAR_P = 0.25  # acceptance floor is 0.05; higher = faster conviction

    async def one_leg(liar: bool, audit_p: float) -> dict:
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(
                model_uid="bench_integ", start=0, end=span_layers,
                params=params, spec=spec, registry=rc(), num_pages=256,
                page_size=PAGE, max_batch=1, integrity=True,
                # the liar advertises the best throughput so routing
                # deterministically picks it first — the worst case the
                # integrity layer must dig the session out of
                throughput=(100.0 if liar and i == 0 else 1.0),
                liar_p=(LIAR_P if liar and i == 0 else 0.0),
                liar_seed=7,
            )
            for i in range(3)
        ]
        for srv in servers:
            await srv.start()
        manager = RemoteSequenceManager(rc(), "bench_integ", span_layers)
        rng = np.random.default_rng(23)
        embed_table = (
            rng.standard_normal((VOCAB_EFF, spec.hidden_size)) * 0.02
        ).astype(np.float32)
        liar_id = servers[0].server_id
        try:
            s = InferenceSession(
                manager, max_length=PROMPT + DECODE + 4, batch_size=1,
                embed_fn=lambda ids: embed_table[np.asarray(ids)],
                audit_p=audit_p, integrity=audit_p > 0,
            )
            tokens: list = []
            hard_failures = 0
            steps_to_quarantine = None
            t0 = time.time()
            async with s:
                ids = rng.integers(0, VOCAB_EFF, size=(1, PROMPT))
                try:
                    out = await s.step(embed_table[ids], ids=ids)
                    for step_i in range(DECODE):
                        # pseudo-head: deterministic greedy selection so
                        # token-identity across legs is meaningful
                        logits = embed_table @ np.asarray(
                            out, dtype=np.float32
                        )[0, -1]
                        nid = np.array([[int(np.argmax(logits))]])
                        tokens.append(int(nid[0, 0]))
                        out = await s.step(embed_table[nid], ids=nid)
                        if (
                            steps_to_quarantine is None
                            and manager.peers_quarantined
                        ):
                            steps_to_quarantine = step_i + 1
                except Exception as e:  # noqa: BLE001
                    hard_failures += 1
                    log(f"integrity: hard failure: {e!r}")
            return {
                "tokens": tokens,
                "wall_s": time.time() - t0,
                "hard_failures": hard_failures,
                "steps_to_quarantine": steps_to_quarantine,
                "sanity_rejects": int(s.sanity_rejects),
                "audits_run": int(s.audits_run),
                "audit_mismatches": int(s.audit_mismatches),
                "integrity_reroutes": int(s.integrity_reroutes),
                "peers_quarantined": int(manager.peers_quarantined),
                "liar_quarantined": liar_id in manager._quarantine,
                "liar_steps": int(servers[0].liar_steps),
            }
        finally:
            for thing in (*servers, reg):
                try:
                    await asyncio.wait_for(thing.stop(), timeout=30.0)
                except Exception:  # noqa: BLE001
                    pass

    clean_off = asyncio.run(one_leg(liar=False, audit_p=0.0))
    clean_on = asyncio.run(one_leg(liar=False, audit_p=1.0))
    liar_leg = asyncio.run(one_leg(liar=True, audit_p=1.0))
    overhead = clean_on["wall_s"] / max(clean_off["wall_s"], 1e-9)
    token_identical = liar_leg["tokens"] == clean_off["tokens"]
    RESULTS["integrity"] = {
        "steps_to_quarantine": liar_leg["steps_to_quarantine"],
        "liar_steps": liar_leg["liar_steps"],
        "sanity_rejects": liar_leg["sanity_rejects"],
        "audits_run": liar_leg["audits_run"],
        "audit_mismatches": liar_leg["audit_mismatches"],
        "integrity_reroutes": liar_leg["integrity_reroutes"],
        "peers_quarantined": liar_leg["peers_quarantined"],
        "audit_overhead_x": overhead,
        "clean_false_positives": (
            clean_on["sanity_rejects"] + clean_on["audit_mismatches"]
        ),
        "token_identical": token_identical,
        "hard_failures": (
            clean_off["hard_failures"] + clean_on["hard_failures"]
            + liar_leg["hard_failures"]
        ),
    }
    assert liar_leg["liar_quarantined"], (
        f"liar NOT quarantined within {DECODE} steps "
        f"(lied {liar_leg['liar_steps']}x, "
        f"{liar_leg['sanity_rejects']} sanity rejects, "
        f"{liar_leg['audit_mismatches']} audit mismatches)"
    )
    assert token_identical, (
        "liar-leg generation diverged from the clean reference: "
        f"{liar_leg['tokens']} vs {clean_off['tokens']}"
    )
    assert RESULTS["integrity"]["hard_failures"] == 0, (
        f"{RESULTS['integrity']['hard_failures']} hard failures"
    )
    assert RESULTS["integrity"]["clean_false_positives"] == 0, (
        "integrity layer false-positived on an honest swarm"
    )
    phase("integrity", "ok")
    log(
        f"integrity: liar quarantined after "
        f"{liar_leg['steps_to_quarantine']} decode steps "
        f"(lied {liar_leg['liar_steps']}x, "
        f"{liar_leg['sanity_rejects']} gate rejects, "
        f"{liar_leg['audit_mismatches']}/{liar_leg['audits_run']} audit "
        f"mismatches); token-identical to clean reference; audit "
        f"overhead {overhead:.2f}x; 0 false positives / hard failures"
    )


def run_served(spec, params, B, PREFILL, DECODE, spans_per_model) -> dict:
    """Registry + BlockServer + client session on loopback: the E2E serving
    path the reference's benchmark_inference.py measures."""
    import asyncio

    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    span_layers = spec.num_hidden_layers

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        # pages sized for the multi-session phase: N_SESS sessions x B seqs
        # x (PREFILL + DECODE + settle/compile steps) tokens
        N_SESS = 6
        SETTLE = 5  # 1 compile + 4 settle decode steps before the timed loop
        # random embed/norm/head trio sized like the real checkpoint: the
        # server-side multi-step decode phase runs the FULL per-token path
        # (embed -> span -> norm+head -> argmax) on device
        import jax as _jax
        import jax.numpy as _jnp

        keys = _jax.random.split(_jax.random.PRNGKey(9), 2)
        client_params = {
            "embed": _jax.random.normal(
                keys[0], (spec.vocab_size, spec.hidden_size), _jnp.bfloat16
            ) * 0.02,
            "norm": _jnp.ones((spec.hidden_size,), _jnp.bfloat16),
            "lm_head": _jax.random.normal(
                keys[1], (spec.hidden_size, spec.vocab_size), _jnp.bfloat16
            ) * 0.02,
        }
        server = BlockServer(
            model_uid="bench", start=0, end=span_layers, params=params,
            spec=spec, registry=rc(), num_pages=768, page_size=16,
            client_params=client_params,
            # the batcher is OFF here so phases A/B stay the per-step and
            # serialized-multisession baselines; phase B2 below measures
            # the same load with continuous batching enabled
            max_batch=1,
        )
        await server.start()
        manager = RemoteSequenceManager(rc(), "bench", span_layers)
        rng = np.random.default_rng(0)
        hidden = rng.standard_normal(
            (B, PREFILL, spec.hidden_size)
        ).astype(np.float32) * 0.02
        step_h = hidden[:, -1:, :]

        # ---- phase A: single-session per-seq latency
        sess = InferenceSession(
            manager, max_length=PREFILL + DECODE + SETTLE, batch_size=B
        )
        async with sess:
            t0 = time.time()
            await sess.step(hidden)  # prefill (compiles the T=128 bucket)
            log(f"served prefill compile+run: {time.time()-t0:.1f}s")
            t0 = time.time()
            await sess.step(step_h)  # compiles the T=1 bucket
            log(f"served first decode compile+run: {time.time()-t0:.1f}s")
            for _ in range(4):  # settle
                await sess.step(step_h)
            sess.timings.clear()  # summarize only steady-state steps
            n_timed = DECODE
            t0 = time.time()
            for _ in range(n_timed):
                await sess.step(step_h)
            elapsed = time.time() - t0
        timing = sess.timing_summary()  # decode-step rows
        steps_per_sec = n_timed / elapsed
        phase("served_per_step", "ok")
        # stash phase-A results now: phase B may wedge the backend
        result = {
            "steps_per_sec": steps_per_sec,
            "equiv_per_seq": steps_per_sec / spans_per_model,
            "per_step_equiv_per_seq": steps_per_sec / spans_per_model,
            "server_decode_chunk": 0,
            "ttft_ms": 0.0,
            "timing": timing,
            "n_sessions": N_SESS,
            "effective_equiv_tok_per_s": steps_per_sec * B / spans_per_model,
        }
        RESULTS["served"] = result

        # ---- phase A2: server-side multi-step decode (decode_n) — the
        # framework's answer to the per-token round-trip floor: one RPC
        # returns CHUNK tokens from an on-device embed->span->head loop
        CHUNK = 8 if DECODE <= 8 else 32
        ROUNDS = max(1, DECODE // CHUNK)
        try:
            sess_sd = InferenceSession(
                manager,
                max_length=PREFILL + CHUNK * (ROUNDS + 2), batch_size=B,
            )
            async with sess_sd:
                await sess_sd.step(hidden)  # prefill (warm bucket)
                t0 = time.time()
                toks = await sess_sd.decode_n(np.zeros((B,), np.int32), CHUNK)
                log(
                    f"served decode_n({CHUNK}) compile+run: "
                    f"{time.time()-t0:.1f}s"
                )
                t0 = time.time()
                for _ in range(ROUNDS):
                    toks = await sess_sd.decode_n(toks[:, -1], CHUNK)
                wall = time.time() - t0
            sd_steps = ROUNDS * CHUNK / wall
            result["server_decode_chunk"] = CHUNK
            result["server_decode_steps_per_sec"] = sd_steps
            # the headline becomes the multi-step served rate; the per-step
            # rate stays on record as per_step_equiv_per_seq
            result["equiv_per_seq"] = sd_steps / spans_per_model
            result["effective_equiv_tok_per_s"] = max(
                result["effective_equiv_tok_per_s"],
                sd_steps * B / spans_per_model,
            )
            phase("served_decode_n", "ok")
            log(
                f"served decode_n: {sd_steps:.1f} steps/s "
                f"({sd_steps / spans_per_model:.1f} 8B-equiv tok/s/seq, "
                f"chunk {CHUNK})"
            )
        except Exception as e:  # noqa: BLE001
            phase("served_decode_n", f"failed: {e!r}"[:200])
            RESULTS.setdefault("degraded", f"decode_n phase failed: {e!r}")
            log(f"served decode_n phase FAILED: {e!r}")

        # ---- phase A3: CHAINED decode_n across a 2-server split of the
        # span — the north-star topology's answer to per-token client RTTs
        # (spans push hidden server-to-server; the tail selects and pushes
        # ids back to span 0; the client pays ONE RTT per chunk)
        srv1 = srv2 = None
        try:
            phase("served_decode_n_chain", "started")
            import jax as __jax

            half = span_layers // 2
            p_lo = __jax.tree.map(lambda x: x[:half], params)
            p_hi = __jax.tree.map(lambda x: x[half:], params)
            srv1 = BlockServer(
                model_uid="bench_chain", start=0, end=half, params=p_lo,
                spec=spec, registry=rc(), num_pages=384, page_size=16,
                client_params=client_params,
            )
            srv2 = BlockServer(
                model_uid="bench_chain", start=half, end=span_layers,
                params=p_hi, spec=spec, registry=rc(), num_pages=384,
                page_size=16, client_params=client_params,
            )
            await srv1.start()
            await srv2.start()
            mgr_ch = RemoteSequenceManager(rc(), "bench_chain", span_layers)
            CH = 8 if DECODE <= 8 else 32
            CH_ROUNDS = max(1, DECODE // CH)
            sess_ch = InferenceSession(
                mgr_ch, max_length=PREFILL + CH * (CH_ROUNDS + 2),
                batch_size=B,
            )
            async with sess_ch:
                await sess_ch.step(hidden)
                t0 = time.time()
                toks = await sess_ch.decode_n(np.zeros((B,), np.int32), CH)
                log(
                    f"chained decode_n({CH}) compile+run: "
                    f"{time.time()-t0:.1f}s"
                )
                t0 = time.time()
                for _ in range(CH_ROUNDS):
                    toks = await sess_ch.decode_n(toks[:, -1], CH)
                wall = time.time() - t0
            ch_steps = CH_ROUNDS * CH / wall
            RESULTS["chain"] = {"steps_per_sec": ch_steps, "chunk": CH}
            phase("served_decode_n_chain", "ok")
            log(
                f"chained decode_n (2 spans): {ch_steps:.1f} steps/s "
                f"(chunk {CH})"
            )
        except Exception as e:  # noqa: BLE001
            phase("served_decode_n_chain", f"failed: {e!r}"[:200])
            RESULTS.setdefault(
                "degraded", f"decode_n_chain phase failed: {e!r}"
            )
            log(f"chained decode_n phase FAILED: {e!r}")
        finally:
            # stop even on failure: two leaked half-span servers would pin
            # their arenas + params through the multi-session phase
            for srv in (srv1, srv2):
                if srv is not None:
                    try:
                        await asyncio.wait_for(srv.stop(), timeout=30.0)
                    except Exception:  # noqa: BLE001
                        pass

        # ---- phase B: N_SESS concurrent sessions — round trips overlap,
        # aggregate throughput approaches the device ceiling (the role of
        # the reference's --n-processes clients, benchmark_inference.py)
        async def one_session():
            s = InferenceSession(
                manager, max_length=PREFILL + DECODE, batch_size=B
            )
            async with s:
                await s.step(hidden)
                for _ in range(DECODE):
                    await s.step(step_h)

        t0 = time.time()
        wedged = False
        # NOT wait_for: cancelling a wedged session would await its close()
        # RPC to the stuck server and hang right back. Abandon instead —
        # the process is about to exit anyway.
        gather_task = asyncio.ensure_future(
            asyncio.gather(*(one_session() for _ in range(N_SESS)))
        )
        done, pending = await asyncio.wait({gather_task}, timeout=300.0)
        if pending:
            wedged = True
            gather_task.cancel()  # best-effort; deliberately not awaited
            phase("multisession", "failed: timed out after 300s")
            RESULTS.setdefault(
                "degraded",
                "multi-session phase timed out after 300s (backend wedged?); "
                "effective number falls back to single-session rate",
            )
            log("multi-session phase TIMED OUT; using single-session rate")
        else:
            gather_task.result()  # propagate real failures
            wall = time.time() - t0
            # count only decode steps (prefills overlap the first decodes)
            eff_steps_per_sec = N_SESS * DECODE / wall
            result["effective_equiv_tok_per_s"] = (
                eff_steps_per_sec * B / spans_per_model
            )
            phase("multisession", "ok")

        # ---- phase B2: continuous batching — the same N_SESS concurrent
        # sessions, against a server that coalesces their single-token
        # decode steps into one merged span dispatch per round (ISSUE 2;
        # BBTPU_BATCH_WINDOW_MS gather window + --max-batch group cap).
        # Reported next to phase B's unbatched aggregate so BENCH_r*.json
        # captures the win.
        if not wedged:
            server_cb = None
            # raw read on purpose: saving the unparsed string to restore
            # after the temporary override below, not reading config
            old_window = os.environ.get(
                "BBTPU_BATCH_WINDOW_MS")  # bbtpu: noqa[BB005]
            try:
                os.environ["BBTPU_BATCH_WINDOW_MS"] = "4"
                server_cb = BlockServer(
                    model_uid="bench_cb", start=0, end=span_layers,
                    params=params, spec=spec, registry=rc(),
                    num_pages=768, page_size=16, max_batch=N_SESS,
                )
                await server_cb.start()
                manager_cb = RemoteSequenceManager(
                    rc(), "bench_cb", span_layers
                )

                async def one_session_cb():
                    s = InferenceSession(
                        manager_cb, max_length=PREFILL + DECODE,
                        batch_size=B,
                    )
                    async with s:
                        await s.step(hidden)
                        for _ in range(DECODE):
                            await s.step(step_h)

                t0 = time.time()
                gather_cb = asyncio.ensure_future(
                    asyncio.gather(
                        *(one_session_cb() for _ in range(N_SESS))
                    )
                )
                done, pending = await asyncio.wait(
                    {gather_cb}, timeout=300.0
                )
                if pending:
                    gather_cb.cancel()  # best-effort, not awaited
                    phase(
                        "multisession_batched",
                        "failed: timed out after 300s",
                    )
                else:
                    gather_cb.result()
                    wall = time.time() - t0
                    eff = N_SESS * DECODE / wall
                    width = server_cb.batched_steps / max(
                        server_cb.batch_dispatches, 1
                    )
                    agg = eff * B / spans_per_model
                    RESULTS["multisession_batched"] = {
                        "agg_equiv_tok_per_s": agg,
                        "unbatched_agg_tok_per_s": result[
                            "effective_equiv_tok_per_s"
                        ],
                        "mean_batch_width": width,
                        "batched_steps": server_cb.batched_steps,
                        "batch_dispatches": server_cb.batch_dispatches,
                        "batch_solo_steps": server_cb.batch_solo_steps,
                        "dispatches_per_token": (
                            server_cb.step_dispatches
                            / max(server_cb.step_tokens, 1)
                        ),
                        "mixed_dispatches": server_cb.mixed_dispatches,
                        "mixed_tokens": server_cb.mixed_tokens,
                        "queue_wait_ms": server_cb.compute.wait_stats_ms(),
                    }
                    log(
                        f"batched multisession: {agg:.1f} equiv tok/s "
                        f"(unbatched "
                        f"{result['effective_equiv_tok_per_s']:.1f}), "
                        f"mean batch width {width:.2f}"
                    )
                    phase("multisession_batched", "ok")
            except Exception as e:  # noqa: BLE001
                phase("multisession_batched", f"failed: {e!r}"[:200])
                log(f"batched multisession phase FAILED: {e!r}")
            finally:
                if old_window is None:
                    os.environ.pop("BBTPU_BATCH_WINDOW_MS", None)
                else:
                    os.environ["BBTPU_BATCH_WINDOW_MS"] = old_window
                if server_cb is not None:
                    try:
                        await asyncio.wait_for(
                            server_cb.stop(), timeout=30.0
                        )
                    except Exception:  # noqa: BLE001
                        pass

        if not wedged:
            # TTFT on a fresh session with warm buckets (skipped when the
            # backend looks wedged — this step would block forever too)
            sess2 = InferenceSession(
                manager, max_length=PREFILL + DECODE, batch_size=B
            )
            async with sess2:
                t0 = time.time()
                await sess2.step(hidden)
                result["ttft_ms"] = (time.time() - t0) * 1000.0
        # teardown can hang on a wedged backend as well — timebox it; the
        # watchdog (or process exit) reaps whatever refuses to die
        for stop in (server.stop, reg.stop):
            try:
                await asyncio.wait_for(stop(), timeout=30.0)
            except Exception:  # noqa: BLE001
                pass
        return result

    return asyncio.run(run())


if __name__ == "__main__":
    main()
