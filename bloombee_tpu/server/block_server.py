"""BlockServer: one worker hosting blocks [start, end) of a model.

Maps the reference worker topology (SURVEY.md sections 3.1/3.3) onto one
asyncio process:

- `rpc_inference` stream == the per-session decode loop
  (reference handler.py:798-1257 + block_functions.py:629). Each step arrives
  either from the client stream or from an upstream server's `rpc_push`
  (server-to-server pipeline, handler.py:1850-2151); the session races both
  sources like the reference's `_iterate_inference_steps`.
- `rpc_push` == upstream activation push; the step metadata carries the
  remaining route so each hop forwards to the next
  (reference `_collect_next_servers`, client/inference_session.py:388-396).
- `rpc_forward` == training-style span forward without a decode session.
- `rpc_info` == ServerInfo snapshot (handler.py:3256 rpc_info).
- A background announcer re-declares the span in the registry every
  `announce_period` with expiration as the liveness signal
  (reference ModuleAnnouncerThread, server.py:914-1007).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import uuid
from collections import OrderedDict

import numpy as np

import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import (
    CacheManager,
    ParkedKVLost,
    SessionKVLost,
)
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.runtime.executor import SpanExecutor, plan_prefill_chunks
from bloombee_tpu.server import artifacts
from bloombee_tpu.server.promotion import PromotionLoopMixin
from bloombee_tpu.server.compute_queue import (
    PRIORITY_INFERENCE,
    PRIORITY_TRAINING,
    ComputeQueue,
    DeadlineExpired,
    aged_chunk_priority,
)
from bloombee_tpu.swarm.data import ServerInfo, ServerState
from bloombee_tpu.utils import clock, env, jitwatch, ledger, lockwatch
from bloombee_tpu.wire.flow import FlowLimiter
from bloombee_tpu.wire.rpc import (
    Connection,
    ConnectionClosed,
    OverloadedError,
    RpcError,
    RpcServer,
    Stream,
    connect,
)
from bloombee_tpu.wire.tensor_codec import name_for_dtype

logger = logging.getLogger(__name__)

env.declare(
    "BBTPU_DUMP_ACTIVATIONS", str, "",
    "directory to dump per-step hidden in/out as .npz (reference "
    "real_activation_dumper); empty = off",
)
env.declare(
    "BBTPU_DUMP_LIMIT", int, 100,
    "max activation dumps per server process",
)
env.declare(
    "BBTPU_PRUNER_TRAIN", bool, False,
    "train the MidLMHead online from accepted speculative paths (reference "
    "lm_head_trainer)",
)
env.declare(
    "BBTPU_PRUNER_CKPT", str, "",
    "pruner-head checkpoint path: loaded at init if present, saved every "
    "50 train steps (the neural scorer uses a '.net' sidecar)",
)
env.declare(
    "BBTPU_PRUNER_METHOD", str, "simple",
    "draft-tree pruning strategy: 'simple' (probability threshold, "
    "reference simple_probability_pruner) or 'neural' (learned MLP over "
    "probability features, reference adaptive_neural_pruner)",
)
env.declare(
    "BBTPU_WEIGHT_QUANT", str, "none",
    "weight-only quantization for served spans: none | int8 (per-column "
    "symmetric, ~2x decode-bandwidth headroom) | int4 (group-wise "
    "asymmetric, ~4x); compute stays bf16 (reference compression.py "
    "weight compression)",
)
env.declare(
    "BBTPU_REPL_INFLIGHT", int, 2,
    "max concurrent standby-replication sweeps per server (the kv_put "
    "sender side of session-KV replication; each sweep holds one export "
    "+ one wire push at a time)",
)
env.declare(
    "BBTPU_LOAD_ADVERT_S", float, 0.0,
    "load-advert cadence: refresh and announce the ServerInfo.load "
    "snapshot (queue waits, depth, batch width, pages free) this often; "
    "the effective announce period becomes min(announce_period, this), so "
    "load telemetry can be fresher than liveness announces (0 = piggyback "
    "on every regular announce only)",
)
env.declare(
    "BBTPU_SESSION_LEASE_S", float, 0.0,
    "session lease: a session whose client stream died (or went silent past "
    "this long with keepalives off) is PARKED — its KV pages are handed to "
    "the prefix pool as evictable refcount-0 cached entries, so a wedged or "
    "partitioned client can never pin memory — and stays resumable "
    "(resume: session_id on a fresh stream) for one more lease period "
    "before final reclaim. 0 disables leases: a dead stream frees the "
    "session immediately (seed behavior). Pair with BBTPU_KEEPALIVE_S so "
    "half-open streams are detected promptly; a lease alone only fences a "
    "session after a full silent lease period",
)
env.declare(
    "BBTPU_MIXED_BATCH", bool, False,
    "mixed-batch dispatch (Sarathi-Serve fused iterations): let a popped "
    "prefill chunk absorb compatible queued single-token decode steps "
    "(and vice versa) into ONE ragged span dispatch "
    "(executor.ragged_group; with --spec-batch also on, tree-verify rows "
    "join the same dispatch), so a mid-stream prefill no longer costs "
    "decodes a whole dispatch each. Falls back to separate dispatches on "
    "configs the ragged step doesn't cover (weight offload, hetero "
    "spans, top-k attention; TP meshes run the fused path via the dense "
    "sharded attend), surfacing each declined reason in rpc_info "
    "ragged_declines. Off = the decode-only batcher and per-chunk "
    "prefill tasks, byte-for-byte",
)
env.declare(
    "BBTPU_PROMOTE_HIGH_MS", float, 1500.0,
    "standby promotion high watermark: a standby promotes itself to a "
    "serving replica when its span's best serving server has sustained "
    "this much predicted queue delay (ms) — or immediately when the "
    "span has NO live serving server (advert silence past the lease)",
)
env.declare(
    "BBTPU_PROMOTE_LOW_MS", float, 200.0,
    "standby demotion low watermark: a promoted standby drains back to "
    "standby once the span's OTHER serving servers have sustained "
    "predicted queue delay below this (ms) and cover every block — "
    "the high/low gap is the promotion hysteresis band",
)
env.declare(
    "BBTPU_PROMOTE_SUSTAIN_S", float, 10.0,
    "how long the hot (cool) condition must hold before a standby "
    "promotes (a promoted replica demotes); one flappy advert window "
    "must not churn replicas",
)
env.declare(
    "BBTPU_PROMOTE_JITTER_S", float, 2.0,
    "promotion-storm guard: a standby sleeps uniform(0, this) seconds "
    "and RE-CHECKS the trigger before declaring itself serving, so N "
    "standbys watching one hot span don't all promote at once (a "
    "peer's promotion clears the trigger for the rest)",
)
env.declare(
    "BBTPU_SPEC_BATCH", bool, False,
    "batched tree-speculative verification: let concurrent sessions' "
    "tree-verify steps that share (layers, adapter, dtype) pad/stack into "
    "ONE ragged span dispatch (executor.ragged_group; with --mixed-batch "
    "also on, tree rows fuse with decode rows and a prefill chunk in the "
    "same dispatch) instead of a solo dispatch per speculating session; "
    "per-session speculative KV still commits/rolls back row-by-row and "
    "the accept-rides-next-step protocol is unchanged. Falls back to solo "
    "tree steps on configs the ragged tree step doesn't cover (weight "
    "offload, hetero spans, top-k attention, sliding-window layers; TP "
    "meshes run the fused path via the dense sharded attend). Off = "
    "every tree-verify step dispatches solo, byte-for-byte",
)
env.declare(
    "BBTPU_LIAR_P", float, 0.0,
    "TEST HOOK (Byzantine fault injection): per-step probability this "
    "server perturbs its span-output hidden states BEFORE serialization "
    "— a well-formed reply carrying wrong numbers, the lie the client "
    "integrity layer (BBTPU_INTEGRITY / BBTPU_AUDIT_P) exists to catch. "
    "Seeded by BBTPU_LIAR_SEED for reproducible chaos runs; never enable "
    "in real serving",
)
env.declare(
    "BBTPU_LIAR_SEED", int, 0,
    "seed for the BBTPU_LIAR_P perturbation RNG (which steps lie and "
    "how), so integrity chaos/bench runs are reproducible",
)


class _ChainError(RuntimeError):
    """A downstream span of a chained decode_n reported failure (pushed
    back as `chain_error`). `permanent` distinguishes capability declines
    (tail has no head params / dtype mismatch — retrying the same route
    can never work, the client should fall back to per-step) from
    transient route failures (a span died mid-chain — the client should
    rebuild, replay, and RETRY chained decode on the fresh route)."""

    def __init__(self, msg: str, permanent: bool = False):
        super().__init__(msg)
        self.permanent = permanent


@dataclasses.dataclass
class _BatchMember:
    """One session's single-token decode step inside a merged dispatch
    (continuous batching). `handle` is the session's cache handle or a row
    slice of it (micro-batch chunks batch like any other member)."""

    session: "_Session"
    handle: object
    hidden: np.ndarray  # [b, 1, D] in the wire dtype


@dataclasses.dataclass
class _ChunkMember:
    """One prefill chunk inside a MIXED dispatch (--mixed-batch): the
    multi-token member that rides a ragged span step alongside other
    sessions' single-token decodes. `first`/`last` carry the chunk
    stream's settle/commit duties into whichever dispatch runs it."""

    session: "_Session"
    handle: object
    hidden: np.ndarray  # [b, t, D] in the wire dtype
    first: bool
    last: bool
    prefix_skip: object = None


@dataclasses.dataclass
class _TreeMember:
    """One session's tree-verify step inside a batched ragged dispatch
    (--spec-batch): the linearized draft tree's rows verify alongside
    other sessions' trees in one executor.tree_group call. `handle` may be
    a row slice of the session handle (the client shrinks the step to its
    live-row window as rows finish)."""

    session: "_Session"
    handle: object
    hidden: np.ndarray  # [b, t, D] in the wire dtype
    tree_mask: np.ndarray  # [b, t, t] bool ancestor-or-self visibility
    depths: np.ndarray  # [b, t] i32 node depths (rotary offsets)


class _Session:
    def __init__(self, session_id: str, handle, batch_size: int,
                 layers: tuple[int, int] | None = None,
                 adapter: str | None = None,
                 client_id: str | None = None):
        self.id = session_id
        self.handle = handle
        self.batch_size = batch_size
        self.layers = layers  # relative (l0, l1) within this server's span
        self.adapter = adapter  # per-request LoRA adapter name (or base)
        # admission-control identity: the client's self-declared id (one
        # per client process) or the session id when an old client sends
        # none — fair-share accounting then degrades to per-session
        self.client_id = client_id or session_id
        self.push_inbox: asyncio.Queue = asyncio.Queue()
        # chained decode_n control messages (the tail span's selected ids /
        # errors) land here directly from rpc_push — NOT via push_inbox,
        # whose consumer (the session loop) is blocked inside the
        # coordinator while it waits for exactly these messages
        self.chain_inbox: asyncio.Queue = asyncio.Queue()
        self.step_tasks: set[asyncio.Task] = set()  # in-flight mb chunks
        self.last_step_at = 0.0  # idle measure for the parking reclaimer
        # per-session timing accumulators (server half of the reference's
        # [TIMING_TABLE] decomposition, handler.py:1276-1605)
        self.n_steps = 0
        self.sum_tokens = 0
        self.sum_dispatch_ms = 0.0
        self.sum_fetch_ms = 0.0
        self.opened_at = 0.0
        # last pruned tree step's (hidden, tokens, parents) for online
        # pruner-head training when its accept arrives
        self.last_tree = None
        # per-session measured speculation: drafted tree tokens this
        # session verified and how many its accepts kept (the server half
        # of the drafter's feedback loop — surfaced via rpc_info so an
        # operator can see which streams speculate productively)
        self.spec_drafted = 0
        self.spec_accepted = 0
        # session-KV replication to a standby (client-directed kv_repl
        # items): standby (host, port), the client's full-history hash
        # chains per row, pages already shipped per row, and a lock so
        # only one sweep drains the backlog at a time
        self.repl_standby: tuple[str, int] | None = None
        self.repl_chains: list[list[str]] | None = None
        self.repl_sent: list[int] | None = None
        self.repl_lock = lockwatch.async_lock("server.repl")
        # session lease / reconnect-resume state. The stream-opening RPC
        # handler OWNS the KV pages (allocate context) and survives stream
        # death: it parks, then waits on resume_waiter for either a resume
        # handler (which hands over its fresh stream) or the lease reaper.
        self.parked = False
        self.reaped = False  # lease expired / resume impossible
        self.lease_deadline = 0.0  # monotonic; meaningful while parked
        self.cur_stream = None  # stream the session loop is serving now
        self.resume_waiter: asyncio.Event | None = None
        self.resume_stream = None  # set by the resume handler before wake
        self.detach_event: asyncio.Event | None = None  # releases the
        # resume handler whose stream the session loop currently serves
        # fencing: bumped per adopted stream so anything captured against
        # an older stream can be recognized as stale
        self.stream_epoch = 0
        # at-most-once step application: replies are recorded (keyed
        # (step, mb)) BEFORE first delivery, so a step retried after a
        # lost ack resends the recorded reply instead of re-applying KV
        self.last_step_id = -1
        self.applied_steps: dict[tuple[int, int], tuple[dict, list]] = {}
        # a stepped decode_n chain died after committing KV the client was
        # never told about: resuming would desync — force full replay
        self.kv_dirty = False
        # prefix-cache adoption is SETTLED once a step has trimmed the
        # adopted prefix to the client's declared skip. Until then the
        # session must step solo (the settle mutates the table); after,
        # it batches like any other session instead of being carved out
        # of merged dispatches for the rest of its life
        self.adoption_settled = False
        # speculation-mode gauge for the kind-aware group_hint: True
        # while the session could contribute a tree-verify row. A gather
        # that can only admit tree rows is bounded by the sessions
        # currently speculating — without this, tree groups sleep the
        # full window whenever any non-speculating session is open.
        # OPTIMISTIC start (True): until a session reveals its kind with
        # a plain decode step it might speculate, and the first tree
        # gathers must wait for it or concurrent spec sessions that start
        # milliseconds apart never pair up
        self.speculating = True


class _PeerPool:
    """Cached outbound connections for server-to-server push.

    Locking is per-peer so one unreachable peer's connect timeout cannot
    stall pushes to healthy peers."""

    def __init__(self):
        self._conns: dict[tuple[str, int], Connection] = {}
        self._locks: dict[tuple[str, int], asyncio.Lock] = {}
        self._limiters: dict[tuple[str, int], FlowLimiter] = {}

    def limiter(self, host: str, port: int) -> FlowLimiter:
        """Per-peer adaptive push limiter (reference handler.py:255-370
        AdaptivePushConcurrency role)."""
        key = (host, port)
        lim = self._limiters.get(key)
        if lim is None:
            lim = self._limiters[key] = FlowLimiter(name=f"{host}:{port}")
        return lim

    async def get(self, host: str, port: int) -> Connection:
        key = (host, port)
        lock = self._locks.get(key)
        if lock is None:
            lock = self._locks[key] = lockwatch.async_lock("server.peer_pool")
        async with lock:
            conn = self._conns.get(key)
            if conn is None or conn.is_closing():
                conn = await connect(host, port)
                self._conns[key] = conn
            return conn

    async def close(self):
        for c in self._conns.values():
            await c.close()
        self._conns.clear()


class BlockServer(PromotionLoopMixin):
    def __init__(
        self,
        *,
        model_uid: str,
        start: int,
        end: int,
        params=None,
        spec: ModelSpec | None = None,
        model_dir: str | None = None,
        registry=None,
        host: str = "127.0.0.1",
        port: int = 0,
        public_host: str | None = None,
        num_pages: int = 256,
        page_size: int = 16,
        compute_dtype=jnp.bfloat16,
        max_chunk_tokens: int = 512,
        max_batch: int = 8,  # continuous batching: coalesce up to this
        # many compatible single-token decode steps (across sessions) into
        # one span dispatch; 1 disables the batcher. The gather window is
        # BBTPU_BATCH_WINDOW_MS (default 0: only already-queued steps
        # coalesce, so idle-server latency is untouched)
        announce_period: float = 5.0,
        alloc_timeout: float = 60.0,
        throughput: float = 1.0,
        adapter_dirs: list[str] | None = None,  # merged into base at load
        adapters: dict[str, str] | None = None,  # name -> dir, per-request
        tp: int = 1,
        sp: int = 1,  # >1: long prefills spread over this many local
        # chips via ring attention (parallel/sp_serving.py); decode stays
        # single-chip paged
        kv_quant: str | None = None,  # "int4" -> quantized KV arena
        weight_quant: str | None = None,  # "int8"/"int4" -> quantized weights
        oversubscribe: float = 1.0,  # admit > capacity; park idle sessions
        idle_park_s: float = 5.0,  # a session this idle may be parked
        attn_sparsity: float = 1.0,  # <1: top-k sparse decode attention
        client_params: dict | None = None,  # embed/norm/lm_head for the
        # server-side multi-step decode loop (decode_n); lazy-loaded from
        # model_dir when omitted
        decode_n_max: int = 256,  # largest decode_n accepted per RPC (a
        # bigger n eagerly commits n KV slots per row before compute, so an
        # unbounded request could exhaust the arena in one call)
        rebalance_period: float = 0.0,  # >0: periodically check whether
        # moving this span to the swarm's least-served window beats the
        # hysteresis margin, and MOVE if so (reference server.py:479-542
        # module_container restart loop); 0 disables
        drain_timeout: float = 30.0,  # how long a rebalance waits for live
        # sessions to finish before swapping the span under them (their
        # next step then gets the typed session_lost and replays elsewhere)
        offload_layers: int = 0,  # stream the span's last N layers' weights
        # from host per step (FlexGen weight-offload: serve spans larger
        # than HBM; combine with --weight-quant to shrink the streamed
        # bytes 2-4x)
        prefix_cache: bool | None = None,  # cross-session shared-prefix KV
        # cache: pool committed prompt pages under content hashes, adopt
        # them into matching sessions, prefill only the suffix
        # (None -> BBTPU_PREFIX_CACHE env; forces the Python paged table)
        prefill_chunk: int | None = None,  # stall-free scheduling
        # (Sarathi-Serve): split each prefill into chunks of at most this
        # many tokens, each its own compute-queue task, so concurrent
        # sessions' decode steps run between chunks instead of stalling
        # behind the whole prompt (0 = monolithic prefill; None ->
        # BBTPU_PREFILL_CHUNK env)
        admit: bool | None = None,  # overload admission control: past
        # admit_high_ms of measured queue delay, shed NEW sessions/prefills
        # with a retriable overloaded(retry_after_ms) instead of letting
        # queue-time deadline aborts kill them; established sessions'
        # decode steps are always admitted (None -> BBTPU_ADMIT env)
        admit_high_ms: float | None = None,  # admission high watermark in
        # ms of live queue delay (None -> BBTPU_ADMIT_HIGH_MS env)
        load_advert_s: float | None = None,  # refresh/announce the
        # ServerInfo.load snapshot this often; effective cadence is
        # min(announce_period, load_advert_s) so load telemetry can be
        # fresher than liveness announces (None -> BBTPU_LOAD_ADVERT_S
        # env; 0 = every announce_period)
        session_lease_s: float | None = None,  # session lifecycle
        # hardening: a session whose stream died is PARKED (pages become
        # evictable cached pool entries) and resumable for this long
        # before final reclaim; also the silence bound past which the
        # reaper fences a live-but-wedged client (None ->
        # BBTPU_SESSION_LEASE_S env; 0 disables)
        keepalive_s: float | None = None,  # wire keepalive interval for
        # accepted connections so half-open clients (partition, no
        # FIN/RST) are detected instead of hanging recv() forever
        # (None -> BBTPU_KEEPALIVE_S env; 0 disables)
        mixed_batch: bool | None = None,  # fuse a prefill chunk and
        # compatible queued decode steps into ONE ragged span dispatch
        # (Sarathi-Serve fused iterations) instead of a dispatch each;
        # falls back to separate dispatches on configs the ragged step
        # doesn't cover (weight offload, hetero spans, top-k attention —
        # TP meshes run the fused path). None -> BBTPU_MIXED_BATCH env;
        # off = current decode-only batching, byte-for-byte
        spec_batch: bool | None = None,  # batched tree-speculative
        # verification: pad/stack concurrent sessions' compatible
        # tree-verify steps into ONE ragged span dispatch
        # (executor.ragged_group — with mixed_batch also on, tree rows
        # fuse with decode rows and a chunk) instead of one solo dispatch
        # per speculating session; falls back to solo tree steps on
        # configs the ragged tree step doesn't cover. None ->
        # BBTPU_SPEC_BATCH env; off = solo tree dispatches, byte-for-byte
        standby: bool = False,  # start as a WARM STANDBY for this span:
        # announce JOINING (holds weights + accepts kv_put replication but
        # takes no routed traffic), watch the span's serving replicas, and
        # self-promote to ONLINE on sustained overload or server loss —
        # then drain back to standby when the span cools (the elastic
        # self-healing control loop)
        promote_high_ms: float | None = None,  # promotion high watermark
        # in ms of the span's best serving server's predicted queue delay
        # (None -> BBTPU_PROMOTE_HIGH_MS env)
        promote_low_ms: float | None = None,  # demotion low watermark
        # (None -> BBTPU_PROMOTE_LOW_MS env)
        promote_sustain_s: float | None = None,  # hot/cool dwell before
        # acting (None -> BBTPU_PROMOTE_SUSTAIN_S env)
        promote_jitter_s: float | None = None,  # storm-guard jitter bound
        # (None -> BBTPU_PROMOTE_JITTER_S env)
        integrity: bool | None = None,  # stamp an out_digest (blake2b over
        # the exact serialized span-output bytes) into every step reply and
        # advertise it, so integrity-enabled clients get a deterministic
        # in-flight-corruption check (None -> BBTPU_INTEGRITY env)
        liar_p: float | None = None,  # TEST HOOK: per-step probability of
        # perturbing span outputs before serialization — the Byzantine
        # "liar" the client audits exist to convict (None -> BBTPU_LIAR_P
        # env; never enable in real serving)
        liar_seed: int | None = None,  # RNG seed for the liar hook
        # (None -> BBTPU_LIAR_SEED env)
        artifact_dir: str | None = None,  # swarm-shared compile-artifact
        # store (doubles as this process's JAX persistent compilation
        # cache dir): serve artifact_get, push artifacts to replication
        # standbys via artifact_put, and pre-install fetched artifacts
        # before warmup so a standby/JOINed server loads executables
        # instead of compiling them (None -> BBTPU_ARTIFACT_DIR env;
        # empty = artifact path off)
    ):
        self.model_dir = model_dir
        if weight_quant is None:
            weight_quant = env.get("BBTPU_WEIGHT_QUANT")
        host_layers: list = []
        if params is None and offload_layers > 0:
            from bloombee_tpu.models.checkpoint import load_span_params_split

            resident = max(0, (end - start) - offload_layers)
            params, host_layers, spec = load_span_params_split(
                model_dir, start, end, resident, dtype=compute_dtype,
                adapter_dirs=adapter_dirs, weight_quant=(
                    None if not weight_quant or weight_quant == "none"
                    else weight_quant
                ),
            )
            weight_quant = "none"  # already applied per layer
        elif params is None:
            from bloombee_tpu.models.checkpoint import load_span_params

            params, spec = load_span_params(
                model_dir, start, end, dtype=compute_dtype,
                adapter_dirs=adapter_dirs,
            )
        elif offload_layers > 0:
            # pre-built params + offload: split the stacked span, move the
            # tail layers to host numpy (the executor streams them back per
            # step with one-ahead prefetch) and free their device copies
            import jax as _jax

            assert spec is not None, "pre-built params need a spec"
            n_span = end - start
            if not 0 < offload_layers <= n_span:
                raise ValueError(
                    f"offload_layers={offload_layers} outside span of "
                    f"{n_span} layers"
                )
            resident = n_span - offload_layers
            host_layers = [
                _jax.tree.map(lambda x, i=i: np.asarray(x[i]), params)
                for i in range(resident, n_span)
            ]
            params = (
                _jax.tree.map(lambda x: x[:resident], params)
                if resident else None
            )
            if weight_quant and weight_quant != "none":
                # quantize BOTH halves here (the later quant block only
                # sees the resident stack — dense host layers would
                # silently keep the full streamed bytes, defeating the
                # point of combining offload with --weight-quant)
                from bloombee_tpu.models import wquant

                bits = {"int8": 8, "int4": 4}[weight_quant]
                if params is not None:
                    params = wquant.quantize_span_params(params, bits)
                host_layers = [
                    _jax.device_get(wquant.quantize_layer_params(h, bits))
                    for h in host_layers
                ]
                weight_quant = "none"  # already applied
        assert spec is not None
        if weight_quant and weight_quant != "none":
            # weight-only quantization (reference compression.py's weight
            # half): decode reads every projection once per token, so int8
            # (int4) storage halves (quarters) HBM bytes per step. Composes
            # with TP (parallel/serving.py place_span_params shards the
            # quantized leaves) and with heterogeneous spans (per-layer
            # dicts quantize via a 1-stack each — attention geometry may
            # vary per layer but each layer quantizes independently anyway)
            from bloombee_tpu.models import wquant

            bits = {"int8": 8, "int4": 4}[weight_quant]
            before = wquant.params_nbytes(params)
            if spec.heterogeneous:
                params = tuple(
                    wquant.quantize_layer_params(p, bits) for p in params
                )
            else:
                params = wquant.quantize_span_params(params, bits)
            logger.info(
                "quantized span weights to %s: %.1f -> %.1f MiB",
                weight_quant, before / 2**20,
                wquant.params_nbytes(params) / 2**20,
            )
        # per-request switchable adapters (reference utils/peft.py
        # `using_adapter` + server --adapters): factors stay UNMERGED so the
        # same base weights serve base and every adapter; a session picks one
        # via open metadata
        self.adapter_factors: dict[str, dict] = {}
        if adapters:
            from bloombee_tpu.models.checkpoint import load_adapter_factors

            for name, adir in adapters.items():
                self.adapter_factors[name] = load_adapter_factors(
                    adir, start, end, dtype=compute_dtype
                )
        self.model_uid = model_uid
        self.start_block = start
        self.end_block = end
        self.spec = spec
        self.server_id = f"srv-{uuid.uuid4().hex[:8]}"
        self.registry = registry
        self.announce_period = announce_period
        self.alloc_timeout = alloc_timeout
        self.public_host = public_host or host
        self.throughput = throughput
        self.inference_rps: float | None = None
        self.compute_dtype = compute_dtype

        self.manager = CacheManager(
            num_layers=end - start,
            num_pages=num_pages,
            page_size=page_size,
            n_kv_heads=spec.num_key_value_heads,
            head_dim=spec.head_dim,
            dtype=compute_dtype,
            quant=kv_quant,
            hetero_spec=spec if spec.heterogeneous else None,
            start_block=start,
            oversubscribe=oversubscribe,
            prefix_cache=prefix_cache,
        )
        self.idle_park_s = idle_park_s
        if oversubscribe > 1.0:
            # serve more sessions than HBM fits: page pressure evicts idle
            # sessions' KV to host (the FlexGen offload story at the
            # session granularity); their next step unparks on demand
            self.manager.reclaimer = self._reclaim_idle
        mesh = None
        if tp > 1:
            # intra-server tensor parallelism over the local chips (ICI):
            # GSPMD-partitioned span step, KV heads + weight shards per chip
            # (reference flexgen_tensor_parallel.py:540-828 role)
            from bloombee_tpu.parallel.serving import make_serving_mesh

            mesh = make_serving_mesh(tp)
        self.tp = tp
        sp_mesh = None
        if sp > 1:
            from bloombee_tpu.parallel.sp_serving import make_sp_mesh

            sp_mesh = make_sp_mesh(sp)
        self.sp = sp
        self.executor = SpanExecutor(
            params, spec, self.manager,
            max_chunk_tokens=max_chunk_tokens,
            compute_dtype=compute_dtype,
            start_block=start,
            mesh=mesh,
            adapters=self.adapter_factors,
            host_layers=host_layers,
            attn_sparsity=attn_sparsity,
            sp_mesh=sp_mesh,
        )
        self.wire_dtype = name_for_dtype(self.executor.transfer_dtype)
        if spec.heterogeneous or host_layers:
            # hetero / weight-offloaded spans: no dense training stack
            self.training = None
        else:
            from bloombee_tpu.runtime.training import TrainingExecutor

            self.training = TrainingExecutor(
                params, spec, windows=self.executor.windows,
                compute_dtype=compute_dtype, adapters=self.adapter_factors,
            )
        self.decode_n_max = int(decode_n_max)
        # per-token budget for a chained decode_n round trip through the
        # downstream spans (generous: the first chain step may hit a cold
        # XLA compile on a middle/tail span)
        self.chain_step_timeout = 120.0
        self.max_batch = max(1, int(max_batch))
        # ragged-path declines, per reason (BB006: rpc_info + health
        # --probe): every requested-but-unsupported fallback to monolithic
        # dispatch is operator-visible instead of a silent logger.info
        self.ragged_declines: dict[str, int] = {}
        if mixed_batch is None:
            mixed_batch = bool(env.get("BBTPU_MIXED_BATCH"))
        if mixed_batch:
            reason = self.executor.mixed_unsupported()
            if reason is not None:
                logger.info(
                    "mixed-batch dispatch disabled: %s", reason
                )
                self.ragged_declines[reason] = (
                    self.ragged_declines.get(reason, 0) + 1
                )
                mixed_batch = False
        self.mixed_batch = bool(mixed_batch)
        if spec_batch is None:
            spec_batch = bool(env.get("BBTPU_SPEC_BATCH"))
        if spec_batch:
            reason = self.executor.tree_group_unsupported()
            if reason is not None:
                logger.info(
                    "batched tree verification disabled: %s", reason
                )
                self.ragged_declines[reason] = (
                    self.ragged_declines.get(reason, 0) + 1
                )
                spec_batch = False
        self.spec_batch = bool(spec_batch)
        if self.mixed_batch or self.spec_batch:
            # ONE kind-aware gather predicate covers every batchable row
            # kind (decode rows, the prefill chunk, tree-verify rows);
            # with --mixed-batch the chunk rides one extra group slot so
            # fusing never costs the batcher any of its max_batch seats
            self.compute = ComputeQueue(
                max_group=self.max_batch + (1 if self.mixed_batch else 0),
                compat=self._ragged_compat,
                group_hint=self._batch_group_hint,
            )
        else:
            self.compute = ComputeQueue(
                max_group=self.max_batch, group_hint=self._batch_group_hint
            )
        self.peers = _PeerPool()
        # server-side multi-step decode (decode_n): needs the checkpoint's
        # embed/norm/lm_head trio; lazy-loaded from model_dir on first use
        self._client_params = client_params
        self._client_params_unavailable = False
        self._client_params_lock: asyncio.Lock | None = None
        # mid-chain draft-tree pruning (reference speculative_pruner/): the
        # MidLMHead weight lazy-loads from the checkpoint's lm_head
        self._pruner_manager = None
        self._pruner_unavailable = False
        self._pruner_lock: asyncio.Lock | None = None
        # measured RTTs to servers of the block after this span, announced
        # in ServerInfo.next_pings for routing (reference server.py:1000-1007
        # ModuleAnnouncerThread next-block pings)
        from bloombee_tpu.swarm.ping import PingAggregator

        self.next_pings = PingAggregator()
        self._sessions: dict[str, _Session] = {}
        self._pending_pushes: dict[str, list] = {}
        self.pending_push_ttl = 30.0
        self._announce_task: asyncio.Task | None = None
        self._supervisor_task: asyncio.Task | None = None
        self._warmup_task: asyncio.Task | None = None
        self._throughput_task: asyncio.Task | None = None
        self.rebalance_period = float(rebalance_period)
        self.drain_timeout = float(drain_timeout)
        self._rebalancing = False
        # graceful shutdown: announces DRAINING (routing stops sending NEW
        # sessions), keeps serving in-flight sessions up to drain_timeout
        self._draining = False
        # chaos harness: crash() flips this; post-crash nothing may take a
        # graceful path (no park, no announce, no revoke)
        self._crashed = False
        # elastic self-healing: standby/promotion control-loop state. A
        # standby announces JOINING (invisible to routing, visible to
        # kv_put replication) and refuses session opens; _promotion_loop
        # flips _standby/_promoted on sustained span overload or loss.
        self._standby = bool(standby)
        self._promoted = False
        self.promote_high_ms = (
            float(env.get("BBTPU_PROMOTE_HIGH_MS"))
            if promote_high_ms is None else float(promote_high_ms)
        )
        self.promote_low_ms = (
            float(env.get("BBTPU_PROMOTE_LOW_MS"))
            if promote_low_ms is None else float(promote_low_ms)
        )
        self.promote_sustain_s = (
            float(env.get("BBTPU_PROMOTE_SUSTAIN_S"))
            if promote_sustain_s is None else float(promote_sustain_s)
        )
        self.promote_jitter_s = (
            float(env.get("BBTPU_PROMOTE_JITTER_S"))
            if promote_jitter_s is None else float(promote_jitter_s)
        )
        self._promotion_task: asyncio.Task | None = None
        # seeded per server: the storm-guard jitter must differ across
        # standbys even when they start in the same millisecond
        self._promote_rng = random.Random(self.server_id)
        # control-loop decision counters (rpc_info + health --probe):
        # every promote/demote/rebalance outcome is operator-visible
        self.promotions = 0
        self.demotions = 0
        self.promotions_yielded = 0
        self.demotions_aborted = 0
        self.rebalances_moved = 0
        self.rebalances_failed = 0
        self.rebalance_skipped_hysteresis = 0
        # work dropped because the client's deadline budget (meta
        # "deadline_s") expired before/while we would compute it; surfaced
        # via rpc_info for operators and the chaos tests
        self.deadlines_expired = 0
        # continuous-batching counters (rpc_info): member steps that shared
        # a merged dispatch, merged dispatches issued, and batcher-routed
        # steps that ran alone (width-1 pops, parked/stale-epoch members,
        # row-by-row replays after a failed merged dispatch)
        self.batched_steps = 0
        self.batch_dispatches = 0
        self.batch_solo_steps = 0
        # stall-free scheduling (chunked prefill): the per-server chunk
        # token budget (None -> BBTPU_PREFILL_CHUNK env, 0 = monolithic),
        # chunk/token counters, decode steps that dispatched while some
        # session's prefill was mid-stream (the interleaving this feature
        # exists for), and the live count of mid-stream chunked prefills
        self.prefill_chunk = prefill_chunk
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.decode_steps_interleaved = 0
        self._chunking_sessions = 0
        # mixed-batch observability: fused ragged dispatches issued, the
        # tokens they carried, and the all-paths dispatch/token totals
        # behind dispatches_per_token (every inference dispatch counts —
        # solo steps, merged decodes, prefill chunks, mixed groups — so
        # the ratio falls exactly when fusing removes dispatches)
        self.mixed_dispatches = 0
        self.mixed_tokens = 0
        self.step_dispatches = 0
        self.step_tokens = 0
        # universal ragged dispatch observability: fused groups run
        # through the unified runner, and how many of them mixed row
        # KINDS (decode/chunk/tree) in one device step — the capability
        # the three legacy paths could never express
        self.ragged_group_dispatches = 0
        self.ragged_cross_kind_dispatches = 0
        # speculative-decode observability (previously client-side only):
        # tree-verify steps served (solo or grouped), the session rows
        # they carried, drafted vs accepted speculative tokens (from the
        # accept metas riding each next step), and the batched-verification
        # group counters behind mean_tree_batch_width
        self.tree_steps = 0
        self.tree_rows = 0
        self.spec_tokens_drafted = 0
        self.spec_tokens_accepted = 0
        self.tree_group_dispatches = 0
        self.tree_group_members = 0
        # per-session acceptance outlives the session: closed sessions'
        # drafted/accepted tallies stay probeable (bounded ring) so an
        # operator can still see which finished streams speculated well
        self._closed_session_spec: "OrderedDict[str, dict]" = OrderedDict()
        # overload protection: the admission controller sheds NEW work
        # past the high watermark (established streams are never routed
        # through it); the load advert republishes live queue gauges
        from bloombee_tpu.server.admission import AdmissionController

        if admit is None:
            admit = bool(env.get("BBTPU_ADMIT"))
        self.admission = (
            AdmissionController(high_ms=admit_high_ms) if admit else None
        )
        self.load_advert_s = (
            float(env.get("BBTPU_LOAD_ADVERT_S"))
            if load_advert_s is None else float(load_advert_s)
        )
        # session-KV replication (fast failover): sealed pages this primary
        # shipped to standbys, and tokens recovering clients replayed into
        # us; the semaphore bounds concurrent replication sweeps so standby
        # traffic can never crowd out live inference
        self.repl_pages_sent = 0
        self.failover_replayed_tokens = 0
        self._repl_sem = asyncio.Semaphore(
            max(1, env.get("BBTPU_REPL_INFLIGHT"))
        )
        # session lifecycle hardening (leases + reconnect-resume): parked
        # sessions reclaimed by the lease reaper, parked sessions
        # re-attached by a reconnecting client, retried steps answered
        # from the recorded reply instead of re-applied, and push items
        # that teardown would otherwise silently discard
        self.session_lease_s = (
            float(env.get("BBTPU_SESSION_LEASE_S"))
            if session_lease_s is None else float(session_lease_s)
        )
        self.sessions_reaped = 0
        self.sessions_resumed = 0
        self.steps_deduped = 0
        self.pushes_dropped = 0
        self._reaper_task: asyncio.Task | None = None
        # integrity layer (server half): digest stamping + the liar test
        # hook. seq_hash_extend_failures surfaces the previously
        # debug-swallowed prefix-hash-chain extension errors (each one
        # silently degrades shared-prefix reuse for later sessions)
        self.integrity = (
            bool(env.get("BBTPU_INTEGRITY"))
            if integrity is None else bool(integrity)
        )
        self.liar_p = (
            float(env.get("BBTPU_LIAR_P")) if liar_p is None
            else float(liar_p)
        )
        self._liar_rng = random.Random(
            env.get("BBTPU_LIAR_SEED") if liar_seed is None else liar_seed
        )
        if self.liar_p > 0:
            logger.warning(
                "BYZANTINE LIAR TEST HOOK ENABLED (liar_p=%.3g): this "
                "server will return corrupted span outputs", self.liar_p,
            )
        self.out_digests_sent = 0
        self.audit_forwards = 0
        self.liar_steps = 0
        self.seq_hash_extend_failures = 0
        # zero-cold-start recovery: the swarm-shared compile-artifact
        # store (server/artifacts.py). Enabling it points JAX's
        # persistent compilation cache at the store dir, so this server's
        # own warmup compiles become servable artifacts with no extra
        # step. warmup_failures counts the per-bucket warmup errors the
        # warmup loop swallows (each one is a bucket that will compile on
        # its first real request — previously invisible behind a bare
        # logger.warning); the artifact_* counters make every install/
        # decline/fallback on the artifact path operator-visible
        if artifact_dir is None:
            artifact_dir = env.get("BBTPU_ARTIFACT_DIR")
        self.artifact_store: artifacts.ArtifactStore | None = None
        if artifact_dir and artifacts.enable_persistent_cache(artifact_dir):
            self.artifact_store = artifacts.ArtifactStore(artifact_dir)
        self._artifacts_preinstalled = False
        self._artifact_pushed_standbys: set[tuple[str, int]] = set()
        self.warmup_failures = 0
        self.artifact_fallback_compiles = 0
        self.artifact_gets_served = 0
        self.artifact_puts_installed = 0
        self.artifact_puts_declined = 0
        self.artifact_blobs_fetched = 0
        self.artifact_fetch_retries = 0
        self._kv_quant = kv_quant
        self._num_pages = num_pages
        self._adapter_dirs = adapter_dirs
        self._weight_quant = weight_quant
        self.rpc = RpcServer(
            unary_handlers={
                "rpc_info": self._rpc_info,
                "rpc_forward": self._rpc_forward,
                "rpc_backward": self._rpc_backward,
                "kv_put": self._kv_put,
                "artifact_get": self._artifact_get,
                "artifact_put": self._artifact_put,
            },
            stream_handlers={"rpc_inference": self._rpc_inference},
            push_handlers={"rpc_push": self._rpc_push},
            host=host,
            port=port,
            keepalive_s=keepalive_s,
        )

    # ---------------------------------------------------------------- lifecycle
    @property
    def port(self) -> int:
        return self.rpc.port

    async def start(self) -> None:
        jitwatch.install()  # no-op unless BBTPU_JITWATCH=1
        await self.rpc.start()
        self.compute.start()
        if self.session_lease_s > 0:
            self._reaper_task = asyncio.create_task(self._lease_reaper_loop())
        if self.registry is not None:
            await self._announce(self._advert_state())
            self._announce_task = asyncio.create_task(self._announce_loop())
            if self._standby:
                self._promotion_task = asyncio.create_task(
                    self._promotion_loop()
                )
            # the announce loop IS the liveness signal: if it dies, the
            # registry record expires and the swarm silently loses this
            # server — supervise and restart it (reference restarts whole
            # unhealthy containers, server.py:524-541); the supervisor
            # also drives periodic rebalancing when enabled
            self._supervisor_task = asyncio.create_task(
                self._supervisor_loop()
            )
        if self.rebalance_period > 0 and self.rebalance_unsupported():
            # fail-loud: the operator asked for auto-balancing but this
            # configuration can never move — silence would hide the loss
            # of the whole feature
            logger.warning(
                "rebalance_period=%.0fs requested but rebalancing is "
                "disabled for this server: %s",
                self.rebalance_period, self.rebalance_unsupported(),
            )
        logger.info(
            "server %s serving %s[%d:%d] on port %d",
            self.server_id, self.model_uid, self.start_block, self.end_block, self.port,
        )

    async def drain(self, timeout: float | None = None) -> None:
        """Graceful shutdown: announce DRAINING so routing stops starting
        NEW sessions here, keep serving the in-flight ones until they
        close (bounded by `timeout`, default drain_timeout), then stop.
        Sessions that outlive the drain replay elsewhere via the client's
        ordinary dead-server recovery path."""

        if self._draining:
            return
        self._draining = True
        deadline = clock.monotonic() + (
            self.drain_timeout if timeout is None else float(timeout)
        )
        logger.info(
            "draining %s: %d in-flight session(s), up to %.0fs",
            self.server_id, len(self._sessions),
            deadline - clock.monotonic(),
        )
        if self.registry is not None:
            try:
                # immediate announce — the periodic loop may be most of an
                # announce_period away, and every new session routed here
                # in that window dies with the server
                await self._announce(ServerState.DRAINING)
            except Exception as e:
                logger.warning("DRAINING announce failed: %s", e)
        # flush pending standby replication FIRST so a standby holds every
        # sealed page a recovering client will probe for — a drained
        # server's sessions fail over with at most the unsealed tail to
        # replay instead of their whole history
        flush = [
            asyncio.create_task(self._replicate_session(s))
            for s in list(self._sessions.values())
            if s.repl_standby is not None
        ]
        if flush:
            try:
                await asyncio.wait_for(
                    asyncio.gather(*flush, return_exceptions=True),
                    timeout=max(1.0, deadline - clock.monotonic()),
                )
            except asyncio.TimeoutError:
                logger.warning(
                    "replication flush outlived the drain window; standbys "
                    "hold a partial backlog"
                )
        # parked sessions have no live client to finish: force-expire their
        # leases NOW so the drain waits only on streams that can still make
        # progress (a wedged session must never eat the whole drain window)
        reaped = 0
        for s in list(self._sessions.values()):
            if s.parked and not s.reaped:
                s.reaped = True
                if s.resume_waiter is not None:
                    s.resume_waiter.set()
                reaped += 1
        if reaped:
            logger.info(
                "drain force-expired %d parked session lease(s)", reaped
            )
        while self._sessions and clock.monotonic() < deadline:
            # sessions parking DURING the drain are refused (the park path
            # checks _draining), so only live streams remain to wait on
            await clock.async_sleep(0.1)
        if self._sessions:
            logger.warning(
                "%d session(s) outlived the drain; they will replay "
                "elsewhere", len(self._sessions),
            )
        await self.stop()

    async def stop(self) -> None:
        for task in (self._supervisor_task, self._warmup_task,
                     self._throughput_task, self._reaper_task,
                     self._promotion_task):
            if task is not None:
                task.cancel()
        if self._announce_task is not None:
            self._announce_task.cancel()
        if self.registry is not None:
            try:
                await self.registry.revoke_blocks(
                    self.model_uid, self.server_id,
                    range(self.start_block, self.end_block),
                    # the tombstone must outlive any replica's stale copy of
                    # our announce (expiration = announce_period * 2.5)
                    expiration=max(60.0, self.announce_period * 2.5 + 10.0),
                )
            except Exception:
                # best-effort: a dead registry at shutdown must not block
                # drain; the announce record expires on its own anyway
                pass
        await self.compute.stop()
        await self.peers.close()
        await self.rpc.stop()

    def crash(self) -> None:
        """Process-crash emulation for the chaos harness: the server dies
        NOW, mid-whatever-it-was-doing. Unlike every graceful path above
        there is no DRAINING announce, no replication flush, no session
        park, no registry revoke (the announce record must expire on its
        own — that silence is what standby promotion watches for), and no
        orderly stream close: every connection's transport is aborted so
        peers see exactly what a kill -9 produces. Sessions and their KV
        are simply lost; recovery happens entirely elsewhere (standby
        promotion, client reroute-replay)."""
        if self._crashed:
            return
        self._crashed = True
        self._draining = True  # refuse any racing open/park/announce
        ledger.fault("server.crash")
        logger.warning("CRASH injected: server %s dying hard", self.server_id)
        for task in (self._supervisor_task, self._warmup_task,
                     self._throughput_task, self._reaper_task,
                     self._promotion_task, self._announce_task):
            if task is not None:
                task.cancel()
        # sessions die unresolved: wake parked resume-waiters so their
        # handler tasks unwind (they observe _crashed and abort), then
        # forget everything — no parking, no lease bookkeeping
        for s in list(self._sessions.values()):
            s.reaped = True
            if s.resume_waiter is not None:
                s.resume_waiter.set()
        self._sessions.clear()
        self.compute.kill()
        self.rpc.abort()

    async def warmup(
        self, batch_sizes=(1,), prefill_tokens: int = 128
    ) -> None:
        """Pre-compile the hot (batch, tokens, pages) buckets so the first
        real request skips multi-second XLA compiles (the role of the
        reference's CUDA-graph warmup + startup throughput measurement,
        throughput.py:244-345). Runs at training priority so any real
        inference outranks it.

        jitwatch phase contract: everything compiled in here is warmup;
        the fence drops when the LAST bucket is in, and any dispatch-
        attributed compile after that is a steady-state recompile the
        --require gate fails on. Re-entrant warmups (elastic rebalance,
        span moves) re-open the warmup phase the same way.

        With an artifact store configured, warmup first pre-installs the
        span's compile artifacts from covering peers (JOIN-time fetch);
        when that succeeds, the bucket loop below LOADS executables from
        the persistent cache instead of compiling them — the
        zero-cold-start path ``jitwatch --require --preinstalled``
        gates. Any fetch failure falls back to plain local compile."""
        jitwatch.install()
        jitwatch.set_phase("warmup")
        if (
            self.artifact_store is not None
            and self.registry is not None
            and not self._artifacts_preinstalled
        ):
            await self.prefetch_artifacts()
        try:
            await self._warmup_buckets(batch_sizes, prefill_tokens)
        finally:
            jitwatch.fence()

    async def _warmup_buckets(
        self, batch_sizes, prefill_tokens: int
    ) -> None:
        for b in batch_sizes:
            try:
                async with self.manager.allocate(
                    b, prefill_tokens + 1, timeout=5.0
                ) as handle:
                    hidden = np.zeros(
                        (b, prefill_tokens, self.spec.hidden_size), np.float32
                    )
                    out = await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.prefill,
                        handle, hidden, True, None, False,
                    )
                    await asyncio.to_thread(self.executor.fetch, out)
                    step = np.zeros((b, 1, self.spec.hidden_size), np.float32)
                    out = await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.decode,
                        handle, step,
                    )
                logger.info("warmed buckets for batch %d", b)
            except Exception as e:
                self._note_warmup_failure()
                logger.warning("warmup(batch=%d) failed: %s", b, e)
        budget = self._chunk_budget()
        if budget > 0 and self.executor.sp_mesh is None:
            # chunked prefill hits buckets the whole-prompt warmup above
            # misses: the chunk-sized token bucket, and (for continuation
            # chunks) the next page bucket up — run a two-chunk prefill so
            # the first real chunked prompt doesn't eat the compile stall
            # this scheduler exists to remove
            try:
                spans = plan_prefill_chunks(
                    2 * budget, budget, cap=self.executor.max_chunk_tokens
                )
                tokens = spans[-1][1]
                async with self.manager.allocate(
                    1, tokens + 1, timeout=5.0
                ) as handle:
                    hidden = np.zeros(
                        (1, tokens, self.spec.hidden_size), np.float32
                    )
                    out = await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.prefill_chunked,
                        handle, hidden, budget, True, None, False,
                    )
                    await asyncio.to_thread(self.executor.fetch, out)
                logger.info(
                    "warmed chunked-prefill buckets (%d chunks of <= %d "
                    "tokens)", len(spans), spans[0][1] - spans[0][0],
                )
            except Exception as e:
                self._note_warmup_failure()
                logger.warning("chunk warmup failed: %s", e)
        if self.executor.sp_mesh is not None:
            # pre-compile the sp-prefill program at its smallest bucket:
            # the whole-span shard_map compile is exactly what would
            # otherwise land on the first long prompt's latency path
            try:
                sp_tokens = int(env.get("BBTPU_SP_MIN_TOKENS"))
                async with self.manager.allocate(
                    1, sp_tokens + 1, timeout=5.0
                ) as handle:
                    hidden = np.zeros(
                        (1, sp_tokens, self.spec.hidden_size), np.float32
                    )
                    await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.prefill,
                        handle, hidden, True, None, False,
                    )
                logger.info("warmed sp prefill (%d tokens)", sp_tokens)
            except Exception as e:
                self._note_warmup_failure()
                logger.warning("sp warmup failed: %s", e)
        await self._warmup_ragged(prefill_tokens)

    async def _warmup_ragged(self, prefill_tokens: int) -> None:
        """Pre-compile the UNIFIED ragged-row buckets the fused group
        paths hit: the grouped-decode packed pair, the decode+chunk
        causal ragged bucket, the default-drafter tree-verify pair, and
        (with BOTH flags on) the cross-kind decode+tree[+chunk] fusions.
        Without this the first fused step after warmup eats the compile
        stall — exactly the steady-state recompile the jitwatch gate
        forbids."""
        mixed_on = self.mixed_batch
        spec_on = self.spec_batch
        if not (mixed_on or spec_on):
            return
        if self.executor.ragged_unsupported(has_tree=spec_on) is not None:
            return
        d = self.spec.hidden_size
        budget = self._chunk_budget() if self.executor.sp_mesh is None else 0
        # default GreedyTreeDrafter branching (2, 2, 1): 11 linearized
        # nodes per tree — the t_max/rb bucket real spec-decode rounds
        # dispatch
        t_i = 11
        cap = prefill_tokens + max(budget, 0) + 24
        try:
            async with self.manager.allocate(
                1, cap, timeout=5.0
            ) as h_a, self.manager.allocate(
                1, cap, timeout=5.0
            ) as h_b, self.manager.allocate(
                1, cap, timeout=5.0
            ) as h_c:
                handles = [h_a, h_b, h_c]
                hidden = np.zeros((1, prefill_tokens, d), np.float32)
                for h in handles:
                    # buckets already warm from the solo pass; this seeds
                    # realistic context depths so pb matches steady state
                    await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.prefill,
                        h, hidden, True, None, False,
                    )

                def tree_rows():
                    return (
                        np.zeros((1, t_i, d), np.float32),
                        np.tril(np.ones((1, t_i, t_i), dtype=bool)),
                        np.arange(t_i, dtype=np.int32)[None, :],
                    )

                async def warm(pairs, label):
                    # pairs: list of (handle, hidden, mask, depths); every
                    # warm dispatch writes KV speculatively, so truncate
                    # each member back afterwards
                    snaps = [
                        [int(x) for x in self.manager.context_lens(h)]
                        for h, _, _, _ in pairs
                    ]
                    await self.compute.submit(
                        PRIORITY_TRAINING, self.executor.ragged_group,
                        [h for h, _, _, _ in pairs],
                        [x for _, x, _, _ in pairs],
                        [m for _, _, m, _ in pairs],
                        [q for _, _, _, q in pairs],
                    )
                    for (h, _, _, _), snap in zip(pairs, snaps):
                        self.manager.truncate_speculative(h, snap)
                    logger.info("warmed ragged buckets: %s", label)

                step = np.zeros((1, 1, d), np.float32)
                chunk = (
                    np.zeros((1, budget, d), np.float32)
                    if budget > 0 else None
                )
                if mixed_on:
                    # pure-decode pair: the packed fast path (grouped
                    # decode), same program _dispatch_batched runs
                    await warm(
                        [(h_a, step, None, None), (h_b, step, None, None)],
                        "decode pair (packed)",
                    )
                    if chunk is not None:
                        await warm(
                            [(h_a, step, None, None),
                             (h_b, chunk, None, None)],
                            "decode + chunk",
                        )
                if spec_on:
                    ta, tb = tree_rows(), tree_rows()
                    await warm(
                        [(h_a,) + ta, (h_b,) + tb],
                        "tree pair",
                    )
                if mixed_on and spec_on:
                    # cross-kind fusions only the universal path runs
                    tb = tree_rows()
                    await warm(
                        [(h_a, step, None, None), (h_b,) + tb],
                        "decode + tree",
                    )
                    if chunk is not None:
                        tc = tree_rows()
                        await warm(
                            [(h_a, step, None, None), (h_b,) + tc,
                             (h_c, chunk, None, None)],
                            "decode + tree + chunk",
                        )
        except Exception as e:
            self._note_warmup_failure()
            logger.warning("ragged warmup failed: %s", e)

    def _note_warmup_failure(self) -> None:
        """Audit a swallowed per-bucket warmup failure: the fence still
        drops (partial warmth beats none), but the bucket that failed
        will compile on its first real request. Counted in rpc_info /
        health --probe and flagged in the jitwatch report as
        warmup_degraded so a zero-recompile green can't mask it."""
        self.warmup_failures += 1
        jitwatch.note_warmup_failure()

    async def _supervisor_loop(self) -> None:
        """Keep the server's background tasks alive and the span balanced.

        - restarts a dead announce loop (its death would silently expire
          this server from the swarm — reference server.py:524-541 restarts
          unhealthy containers; here only the loop needs restarting)
        - surfaces warmup/throughput task failures (one-shots: logged loud,
          not restarted)
        - every rebalance_period seconds, checks whether moving the span
          to the least-served window beats the hysteresis and moves
          (reference server.py:479-542)."""

        last_rebalance = clock.monotonic()
        tick = max(1.0, min(self.announce_period, 15.0))
        while True:
            await clock.async_sleep(tick)
            try:
                self._supervisor_tick()
                if (
                    self.rebalance_period > 0
                    and not self._rebalancing
                    and not self._standby
                    and self.rebalance_unsupported() is None
                    and clock.monotonic() - last_rebalance
                    >= self.rebalance_period
                ):
                    last_rebalance = clock.monotonic()
                    from bloombee_tpu.server.block_selection import (
                        rebalance_if_needed,
                    )

                    moved = await rebalance_if_needed(self)
                    if moved:
                        logger.info(
                            "rebalanced to [%d:%d)",
                            self.start_block, self.end_block,
                        )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a transient registry flap (fetch/announce/declare error)
                # must never kill the supervisor — it is the task that
                # restarts everything else. Log and retry next tick.
                logger.warning("supervisor tick failed: %s", e)

    def _supervisor_tick(self) -> None:
        """One supervision pass: restart dead background loops, surface
        one-shot task failures."""
        if self._announce_task is not None and self._announce_task.done():
            exc = (
                None if self._announce_task.cancelled()
                else self._announce_task.exception()
            )
            logger.error(
                "announce loop died (%s); restarting — without it this "
                "server would silently expire from the registry", exc,
            )
            self._announce_task = asyncio.create_task(
                self._announce_loop()
            )
        if (
            self._promotion_task is not None
            and self._promotion_task.done()
            and (self._standby or self._promoted)
        ):
            exc = (
                None if self._promotion_task.cancelled()
                else self._promotion_task.exception()
            )
            logger.error(
                "promotion loop died (%s); restarting — without it a "
                "standby never promotes and a promoted replica never "
                "drains back", exc,
            )
            self._promotion_task = asyncio.create_task(
                self._promotion_loop()
            )
        for name in ("_warmup_task", "_throughput_task"):
            task = getattr(self, name)
            if task is not None and task.done():
                setattr(self, name, None)  # report once
                if not task.cancelled() and task.exception() is not None:
                    logger.error(
                        "%s failed: %s", name.strip("_"),
                        task.exception(),
                    )

    def rebalance_unsupported(self) -> str | None:
        """Why this server cannot move its span at runtime; None if it can."""
        if self.model_dir is None:
            return "no model_dir to load a new span from"
        if self.executor.host_layers:
            return "weight-offloaded span"
        if self.executor.mesh is not None:
            return "TP-sharded span"
        if self.spec.heterogeneous:
            return "heterogeneous span"
        if self.adapter_factors:
            return "per-request adapters are span-sliced"
        if self._weight_quant and self._weight_quant != "none":
            return "weight-quantized span"
        return None

    async def rebalance_to(self, start: int, end: int) -> None:
        """Move this server to blocks [start, end): tombstone the old span,
        drain sessions (bounded), load the new span's params, swap the
        manager/executor/training stack, and re-announce. Sessions that
        outlive the drain get the typed session_lost on their next step
        (their seq ids are unknown to the fresh manager) and replay onto
        other servers — the same client path that handles a dead server."""
        reason = self.rebalance_unsupported()
        if reason is not None:
            raise RuntimeError(f"rebalance unsupported: {reason}")
        self._rebalancing = True
        try:
            logger.info(
                "rebalancing %s [%d:%d) -> [%d:%d)",
                self.server_id, self.start_block, self.end_block, start, end,
            )
            old_range = range(self.start_block, self.end_block)
            if self.registry is not None:
                try:
                    await self.registry.revoke_blocks(
                        self.model_uid, self.server_id, old_range,
                        expiration=max(
                            60.0, self.announce_period * 2.5 + 10.0
                        ),
                    )
                except Exception as e:
                    logger.warning("revoke of old span failed: %s", e)

            deadline = clock.monotonic() + self.drain_timeout
            while self._sessions and clock.monotonic() < deadline:
                await clock.async_sleep(0.25)
            if self._sessions:
                logger.warning(
                    "%d session(s) outlived the %.0fs drain; they will "
                    "replay elsewhere", len(self._sessions),
                    self.drain_timeout,
                )
            from bloombee_tpu.models.checkpoint import load_span_params

            params, spec = await asyncio.to_thread(
                load_span_params, self.model_dir, start, end,
                self.compute_dtype, self._adapter_dirs,
            )
            manager = CacheManager(
                num_layers=end - start,
                num_pages=self._num_pages,
                page_size=self.manager.page_size,
                n_kv_heads=spec.num_key_value_heads,
                head_dim=spec.head_dim,
                dtype=self.compute_dtype,
                quant=self._kv_quant,
                start_block=start,
                oversubscribe=self.manager.oversubscribe,
                prefix_cache=self.manager.prefix_cache,
            )
            if self.manager.reclaimer is not None:
                manager.reclaimer = self._reclaim_idle
            executor = SpanExecutor(
                params, spec, manager,
                max_chunk_tokens=self.executor.max_chunk_tokens,
                compute_dtype=self.compute_dtype,
                start_block=start,
                attn_sparsity=self.executor.attn_sparsity,
            )
            from bloombee_tpu.runtime.training import TrainingExecutor

            training = TrainingExecutor(
                params, spec, windows=executor.windows,
                compute_dtype=self.compute_dtype,
            )
            # swap atomically from the event loop's view; any step already
            # queued against the old stack fails its epoch check (the new
            # manager knows none of the old seq ids) and replies
            # session_lost
            self.manager = manager
            self.executor = executor
            self.training = training
            self.start_block = start
            self.end_block = end
            self.spec = spec
            if self.registry is not None:
                await self._announce(ServerState.ONLINE)
                ledger.recovery("server.rebalance_reannounce")
        except Exception:
            # mid-move crash: whatever span is actually loaded right now
            # (the OLD one unless the swap already landed — the swap is
            # atomic from the event loop's view) must get back into the
            # registry IMMEDIATELY, not an announce period from now: the
            # revoke above tombstoned it, so until a re-announce the swarm
            # believes this server serves nothing
            if self.registry is not None:
                try:
                    await self._announce(self._advert_state())
                except Exception as e:
                    logger.warning(
                        "re-announce after failed rebalance ALSO failed "
                        "(%s); the periodic announce loop will retry", e,
                    )
            raise
        finally:
            self._rebalancing = False

    def load_snapshot(self) -> dict:
        """Live load gauges republished in every advert (ServerInfo.load)
        and consumed by the client router's predicted-queue-delay term.
        Wall-clock `ts` lets readers staleness-discount the whole dict."""

        waits = self.compute.wait_stats_ms()
        window_s = (
            self.admission.window_s if self.admission is not None else 5.0
        )
        delay_ms = self.compute.current_delay_ms(window_s)
        table = getattr(self.manager, "table", None)
        pages_free = getattr(table, "free_pages", None)
        return {
            "ts": clock.now(),
            "delay_ms": round(delay_ms, 3),
            "queue_depth": self.compute.depth(),
            "wait_ms": {"p50": waits["p50"], "p95": waits["p95"]},
            "prefill_wait_ms": waits["prefill"],
            "decode_wait_ms": waits["decode"],
            "mean_batch_width": round(
                self.batched_steps / self.batch_dispatches
                if self.batch_dispatches else 0.0, 3,
            ),
            "chunk_streams": self._chunking_sessions,
            "pages_free": int(pages_free) if pages_free is not None else None,
            "active_sessions": len(self._sessions),
            # parked sessions hold no pinned pages (their KV sits in the
            # pool as evictable cached entries) — routers can discount them
            "parked_sessions": sum(
                1 for s in self._sessions.values() if s.parked
            ),
            "shedding": bool(
                self.admission is not None
                and delay_ms >= self.admission.high_ms
            ),
        }

    def _advert_state(self) -> ServerState:
        """The state this server should announce right now. JOINING is the
        standby advert: below ONLINE, so routing/spans filters keep the
        server invisible to traffic, while clients scanning for
        replication targets (pick_standby) still see it — no new enum
        value, so old peers parse standby adverts fine."""
        if self._draining:
            return ServerState.DRAINING
        if self._standby:
            return ServerState.JOINING
        return ServerState.ONLINE

    def server_info(self) -> ServerInfo:
        return ServerInfo(
            load=self.load_snapshot(),
            state=self._advert_state(),
            # promoted replicas yield in storm resolution and drain back
            # first when the span cools; the primary never demotes
            promoted_standby=self._promoted,
            host=self.public_host,
            port=self.port,
            throughput=self.throughput,
            inference_rps=self.inference_rps,
            cache_tokens_left=self.manager.tokens_left,
            start_block=self.start_block,
            end_block=self.end_block,
            wire_dtype=self.wire_dtype,
            next_pings=self.next_pings.to_wire() or None,
            adapters=sorted(self.adapter_factors) or None,
            decode_n_max=self.decode_n_max,
            # clients need the page geometry to build prefix hash chains
            # (0 advertises "no prefix cache here")
            page_size=(
                self.manager.page_size if self.manager.prefix_cache else 0
            ),
            # clients only pick standbys that can actually install kv_put
            # pages; a draining server is about to leave the swarm and
            # must not attract fresh replication traffic
            kv_repl=self.manager.repl_supported and not self._draining,
            # integrity-enabled clients verify our replies' out_digest
            # stamps; old clients drop the field (from_wire filtering)
            out_digest=self.integrity,
            # JOINing servers/standbys fetch compile artifacts from peers
            # advertising a store; a draining server is about to leave
            # and must not attract artifact fetch traffic
            artifacts=self.artifact_store is not None and not self._draining,
        )

    async def _announce(self, state: ServerState) -> None:
        info = self.server_info()
        info.state = state
        await self.registry.declare_blocks(
            self.model_uid,
            self.server_id,
            range(self.start_block, self.end_block),
            info,
            expiration=self.announce_period * 2.5,
        )

    async def _announce_loop(self) -> None:
        while True:
            period = self.announce_period
            if self.load_advert_s > 0:
                # faster advert cadence so routing reacts to hot servers
                # within the load window, not a liveness period later; the
                # registry expiration stays announce_period * 2.5, so extra
                # announces only ever REFRESH liveness, never shorten it
                period = min(period, self.load_advert_s)
            await clock.async_sleep(period)
            if self._rebalancing:
                # mid-move: announcing the OLD span would overwrite the
                # tombstone (registry merge is latest-write-wins) and keep
                # routing new sessions onto blocks we are abandoning —
                # exactly defeating the drain. rebalance_to re-announces
                # the new span itself when the swap lands.
                continue
            try:
                # announce FIRST (liveness must not wait on pings — a slow
                # successor would expire our registry record); the pings
                # measured after ride the NEXT announce
                await self._announce(self._advert_state())
                if env.log_channel_enabled("transport"):
                    from bloombee_tpu.wire.tensor_codec import transport_stats

                    logger.info("[transport] %s", transport_stats())
                if env.log_channel_enabled("memory"):
                    from bloombee_tpu.utils.memory import (
                        format_report,
                        server_memory_report,
                    )

                    logger.info(
                        "[memory] %s",
                        format_report(server_memory_report(self)),
                    )
                await asyncio.wait_for(
                    self._measure_next_pings(), self.announce_period
                )
            except asyncio.TimeoutError:
                pass
            except Exception as e:
                logger.warning("announce failed: %s", e)

    async def _measure_next_pings(self) -> None:
        """Ping servers holding the block right after this span so routing
        can cost our push hop with real RTTs."""
        try:
            infos = await self.registry.get_module_infos(
                self.model_uid, [self.end_block]
            )
        except Exception:
            return
        if not infos or not infos[0].servers:
            return
        peers = [
            (sid, info.host, info.port)
            for sid, info in infos[0].servers.items()
            if sid != self.server_id and self.next_pings.needs_measure(sid)
        ][:8]
        if peers:
            await self.next_pings.measure_many(peers)

    # ------------------------------------------------------------------- RPCs
    async def _rpc_info(self, meta: dict, tensors):

        from bloombee_tpu.wire.tensor_codec import transport_stats

        fused_decline = self._decode_n_ineligible()
        params_ok = not self._client_params_unavailable and (
            self._client_params is not None or self.model_dir is not None
        )
        whole = (
            self.start_block == 0
            and self.end_block == self.spec.num_hidden_layers
        )
        info = {
            "server_id": self.server_id,
            "server_time": clock.now(),  # NTP-style clock sync anchor
            "transport": transport_stats(),
            # off-loop codec pipeline counters (wire/pipeline.py): job
            # counts, max observed decode-queue depth, backpressure waits,
            # and the adaptive send-concurrency ceiling across accepted
            # connections
            "wire_pipeline": self.rpc.pipeline_stats(),
            # chaos/ops observability: expired-deadline work drops and the
            # drain flag (also visible as state=DRAINING in server_info)
            "deadlines_expired": self.deadlines_expired,
            "draining": self._draining,
            # elastic self-healing observability: standby/promoted role
            # flags plus the control-loop decision counters (promotion
            # storms resolve as promotions_yielded; drain-backs blocked by
            # live sessions as demotions_aborted)
            "standby": self._standby,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "promotions_yielded": self.promotions_yielded,
            "demotions_aborted": self.demotions_aborted,
            "rebalances_moved": self.rebalances_moved,
            "rebalances_failed": self.rebalances_failed,
            "rebalance_skipped_hysteresis": self.rebalance_skipped_hysteresis,
            # session lifecycle observability (leases/keepalives/resume):
            # leases reaped, parked sessions re-attached, retried steps
            # answered from the recorded reply, keepalive pings sent on
            # accepted conns, pushed items rescued at loop teardown, and
            # the live session age/idle/parked gauges
            "sessions_reaped": self.sessions_reaped,
            "sessions_resumed": self.sessions_resumed,
            "steps_deduped": self.steps_deduped,
            "keepalives_sent": self.rpc.keepalives_sent,
            "pushes_dropped": self.pushes_dropped,
            "session_lease_s": self.session_lease_s,
            **self._session_ages(),
            # continuous-batching observability: how often concurrent
            # sessions' decode steps shared one span dispatch, and how long
            # steps sat in the compute queue (ms percentiles)
            "batched_steps": self.batched_steps,
            "batch_dispatches": self.batch_dispatches,
            "batch_solo_steps": self.batch_solo_steps,
            "mean_batch_width": (
                self.batched_steps / self.batch_dispatches
                if self.batch_dispatches else 0.0
            ),
            # includes per-class sub-dicts ("prefill"/"decode"): bounded
            # decode wait DURING a long prefill is the stall-free signal
            "queue_wait_ms": self.compute.wait_stats_ms(),
            # stall-free scheduling observability (chunked prefill):
            # chunk tasks run, prompt tokens prefilled through the chunked
            # path, and decode steps that dispatched while a prefill was
            # mid-stream (> 0 means prefills no longer head-of-line-block)
            "prefill_chunks": self.prefill_chunks,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "decode_steps_interleaved": self.decode_steps_interleaved,
            # mixed-batch observability: fused decode+prefill dispatches,
            # the tokens they carried, and dispatches_per_token over ALL
            # inference dispatches (1.0 from pure single-token decodes;
            # drops as chunking and fusing pack more tokens per dispatch)
            "mixed_batch": self.mixed_batch,
            "mixed_dispatches": self.mixed_dispatches,
            "mixed_tokens": self.mixed_tokens,
            "step_dispatches": self.step_dispatches,
            "step_tokens": self.step_tokens,
            "dispatches_per_token": (
                self.step_dispatches / max(self.step_tokens, 1)
            ),
            # universal ragged dispatch observability: every fused ragged
            # dispatch, the subset that actually crossed row kinds
            # (decode/tree/chunk in one device step), and every
            # requested-but-declined ragged path keyed by the executor's
            # unsupported reason (non-empty means an operator asked for
            # fusing on a span that can't run it)
            "ragged_group_dispatches": self.ragged_group_dispatches,
            "ragged_cross_kind_dispatches": self.ragged_cross_kind_dispatches,
            "ragged_declines": dict(self.ragged_declines),
            # spec-decode observability (batched tree verification):
            # tree-verify steps served, the session rows they carried,
            # drafted vs accepted speculative tokens (from the accept
            # metas riding each next step — the server half of the
            # drafter's feedback loop), and the batched-group counters
            # (mean_tree_batch_width > 1 means sessions actually fused)
            "spec_batch": self.spec_batch,
            "tree_steps": self.tree_steps,
            "tree_rows": self.tree_rows,
            "spec_tokens_drafted": self.spec_tokens_drafted,
            "spec_tokens_accepted": self.spec_tokens_accepted,
            "spec_accept_rate": (
                self.spec_tokens_accepted / max(self.spec_tokens_drafted, 1)
            ),
            "tree_group_dispatches": self.tree_group_dispatches,
            "tree_group_members": self.tree_group_members,
            "mean_tree_batch_width": (
                self.tree_group_members / self.tree_group_dispatches
                if self.tree_group_dispatches else 0.0
            ),
            # per-session measured acceptance, keyed by session id: which
            # streams speculate(d) productively (a cold stream's low rate
            # is the signal the client's auto-tuner shrinks on); recently
            # closed sessions stay visible via the bounded teardown ring
            "session_spec": {
                **dict(self._closed_session_spec),
                **{
                    sid: {
                        "drafted": s.spec_drafted,
                        "accepted": s.spec_accepted,
                        "accept_rate": (
                            s.spec_accepted / max(s.spec_drafted, 1)
                        ),
                    }
                    for sid, s in self._sessions.items()
                    if s.spec_drafted
                },
            },
            # prefix-cache observability: sessions that adopted pooled
            # prompt pages, tokens they skipped prefilling, copy-on-write
            # page splits, and current cached-pool occupancy (plus
            # repl_pages_installed — kv_put pages accepted as a standby)
            **self.manager.prefix_stats(),
            # kv-replication observability (fast failover): sealed pages
            # shipped to standbys, the current sealed-but-unshipped
            # backlog, and tokens recovering clients replayed into us
            "repl_pages_sent": self.repl_pages_sent,
            "repl_lag_pages": self._repl_lag(),
            "failover_replayed_tokens": self.failover_replayed_tokens,
            # integrity observability: digest stamps emitted, audit
            # re-executions served to verifying clients, liar-hook
            # perturbations injected (test runs only), and prefix
            # hash-chain extensions that failed (silent shared-prefix
            # degradation until this surfaced it)
            "integrity": self.integrity,
            "out_digests_sent": self.out_digests_sent,
            "audit_forwards": self.audit_forwards,
            "liar_steps": self.liar_steps,
            "seq_hash_extend_failures": self.seq_hash_extend_failures,
            # warmup/artifact observability: swallowed per-bucket warmup
            # failures (each is a bucket that compiles on its first real
            # request), plus the compile-artifact path — blobs served/
            # installed/fetched, declines, per-peer fetch retries, the
            # ledgered local-compile fallbacks, and the bounded store's
            # occupancy/eviction gauges
            "warmup_failures": self.warmup_failures,
            "artifact_preinstalled": self._artifacts_preinstalled,
            "artifact_fallback_compiles": self.artifact_fallback_compiles,
            "artifact_gets_served": self.artifact_gets_served,
            "artifact_puts_installed": self.artifact_puts_installed,
            "artifact_puts_declined": self.artifact_puts_declined,
            "artifact_blobs_fetched": self.artifact_blobs_fetched,
            "artifact_fetch_retries": self.artifact_fetch_retries,
            "artifact_store_bytes": (
                self.artifact_store.total_bytes()
                if self.artifact_store is not None else 0
            ),
            "artifact_evictions": (
                self.artifact_store.evictions
                if self.artifact_store is not None else 0
            ),
            "artifact_store_declined": (
                self.artifact_store.declined
                if self.artifact_store is not None else 0
            ),
            # lock-witness observability (BBTPU_LOCKWATCH=1): distinct
            # acquisition-order edges observed in this process and
            # hierarchy violations + cycles; both zero (and harmless)
            # when the witness is off, so probes need no conditionals
            **lockwatch.counters(),
            **jitwatch.counters(),
            # overload observability: shed/admit counters, retry_after
            # histogram, and per-client fair-share debt (None with the
            # admission controller off; the live load snapshot itself rides
            # in via server_info().to_wire()'s "load" key below)
            "admission": (
                self.admission.stats() if self.admission is not None else None
            ),
            # operator visibility into the decode_n fast paths: a client
            # falling back to per-step decoding is otherwise invisible.
            # decode_n: ANY single-span flavor (fused scan or host-driven
            # stepped loop); decode_n_first/last: the chained-decode roles
            # this span can play in a multi-server route
            "decode_n": whole and params_ok,
            "decode_n_fused": fused_decline is None,
            "decode_n_first": self.start_block == 0 and params_ok,
            "decode_n_last": (
                self.end_block == self.spec.num_hidden_layers and params_ok
            ),
            **self.server_info().to_wire(),
        }
        if fused_decline is not None:
            info["decode_n_decline"] = fused_decline
        from bloombee_tpu.utils.memory import server_memory_report

        # operator-pollable memory accounting (reference memory_usage.py's
        # logging surface, as a remote field instead of a local probe)
        info["memory"] = server_memory_report(self)
        if self._client_params is not None:
            info["head_dtype"] = str(self._client_params["lm_head"].dtype)
        return info, []

    # -------------------------------------------- session-KV replication
    async def _kv_put(self, meta: dict, tensors):
        """Standby side of session-KV replication: install hash-addressed
        sealed pages from a primary into the prefix pool as refcount-0
        cached entries. Cached pages are evictable, so replication can
        never OOM a healthy standby — a degraded pool just means a longer
        replay on failover. Declines (installed=0 + reason) instead of
        erroring so mixed swarms degrade to full replay."""
        decline = None
        if self._draining:
            decline = "draining"
        elif not self.manager.repl_supported:
            decline = (
                "kv replication unsupported (prefix cache off, quantized "
                "or heterogeneous arena)"
            )
        elif int(meta.get("page_size", 0)) != self.manager.page_size:
            decline = "page_size mismatch"
        elif (
            int(meta.get("start", -1)) != self.start_block
            or int(meta.get("end", -1)) != self.end_block
        ):
            decline = "span mismatch"
        if decline is not None:
            return {"installed": 0, "reason": decline}, []
        hashes = [str(h) for h in (meta.get("hashes") or [])]
        if not hashes or len(tensors) != 2:
            return {"installed": 0, "reason": "empty or malformed payload"}, []
        k = np.asarray(tensors[0])
        v = np.asarray(tensors[1])
        try:
            installed = await self.compute.submit(
                PRIORITY_TRAINING,
                self.manager.install_replicated, hashes, k, v,
            )
        except ValueError as e:
            return {"installed": 0, "reason": str(e)}, []
        return {"installed": int(installed)}, []

    def _note_kv_repl(self, session: _Session, repl: dict) -> None:
        """Primary side: a client's kv_repl stream item names the standby
        and carries each row's full-history page-hash chain. Publish our
        own freshly-sealed decode pages into the local pool under those
        hashes (so a future session can adopt them here too), then sweep
        the backlog to the standby in the background."""
        standby = repl.get("standby") or {}
        chains = [list(c) for c in (repl.get("chains") or [])]
        if not standby.get("host") or not chains:
            return
        if (
            session.repl_sent is None
            or len(session.repl_sent) != len(chains)
        ):
            session.repl_sent = [0] * len(chains)
        session.repl_standby = (str(standby["host"]), int(standby["port"]))
        session.repl_chains = chains
        try:
            self.manager.extend_seq_hashes(session.handle, chains)
        except Exception as e:
            # non-fatal (replication still runs on the client's chains) but
            # NOT silent: each failure quietly degrades shared-prefix reuse
            # for every later session, so surface it via rpc_info/--probe
            self.seq_hash_extend_failures += 1
            logger.warning(
                "extend_seq_hashes failed (%d so far): %s",
                self.seq_hash_extend_failures, e,
            )
        if (
            self.artifact_store is not None
            and session.repl_standby not in self._artifact_pushed_standbys
        ):
            # one-time per standby: ship the compile-artifact set
            # alongside the KV pages, so a later promotion warms by
            # loading executables instead of compiling them
            self._artifact_pushed_standbys.add(session.repl_standby)
            push = asyncio.create_task(
                self._push_artifacts(session.repl_standby)
            )
            session.step_tasks.add(push)
            push.add_done_callback(session.step_tasks.discard)
        task = asyncio.create_task(self._replicate_session(session))
        # step_tasks membership matters: the session loop gathers these
        # before the allocate context frees the pages a sweep is exporting
        session.step_tasks.add(task)
        task.add_done_callback(session.step_tasks.discard)

    async def _replicate_session(self, session: _Session) -> None:
        """Drain the session's replication backlog. Serialized per session
        (repl_sent is the only progress state); re-sweeps until no pages
        ship, since the chains may grow while a sweep is in flight."""
        if session.repl_lock.locked():
            return  # an earlier trigger is still draining the backlog
        async with session.repl_lock:
            # BB009 owner: block-server team. The chain reaches
            # Connection.call's wire serialization, but repl_lock is a
            # per-session drain latch (sole contender is a concurrent
            # trigger, which bails on locked() above) — nothing convoys
            # behind it, and payload size is bounded by _repl_sem plus
            # the per-pass page budget.
            while await self._replicate_pass(session):  # bbtpu: noqa[BB009]
                pass

    async def _replicate_pass(self, session: _Session) -> bool:
        """One sweep over the session's rows; True when any pages shipped
        (caller sweeps again). Failures leave repl_sent untouched so the
        next kv_repl trigger retries; a standby DECLINE stops replication
        for this session — the client re-picks a standby on recovery."""
        standby = session.repl_standby
        chains = session.repl_chains
        sent_by_row = session.repl_sent
        if standby is None or not chains or sent_by_row is None:
            return False
        ps = self.manager.page_size
        seq_ids = session.handle.seq_ids
        progress = False
        for row, chain in enumerate(chains):
            if row >= len(seq_ids) or row >= len(sent_by_row):
                break
            sent = sent_by_row[row]
            if sent >= len(chain):
                continue
            async with self._repl_sem:
                try:
                    res = await self.compute.submit(
                        PRIORITY_TRAINING, self.manager.export_pages,
                        seq_ids[row], sent, len(chain),
                    )
                except Exception as e:
                    logger.debug("kv replication export failed: %s", e)
                    return False
                if res is None:
                    continue  # row parked/adopted/unsupported — skip
                k_dev, v_dev, hi = res
                n = int(hi) - sent
                if n <= 0:
                    continue
                # device [L, n*ps, kv, hd] -> host [n, L, ps, kv, hd]
                # (one leading page axis so the standby scatters per hash)
                def _export(dev, n=n, ps=ps):
                    a = np.asarray(dev)
                    shape = (a.shape[0], n, ps) + a.shape[2:]
                    # the swapaxes copy is O(pages shipped) host work —
                    # keep it on the same worker thread as the d2h pull,
                    # not the event loop
                    return np.ascontiguousarray(
                        np.swapaxes(a.reshape(shape), 0, 1)
                    )

                k = await asyncio.to_thread(_export, k_dev)
                v = await asyncio.to_thread(_export, v_dev)
                try:
                    conn = await self.peers.get(*standby)
                    reply, _ = await conn.call(
                        "kv_put",
                        {
                            "page_size": ps,
                            "start": self.start_block,
                            "end": self.end_block,
                            "hashes": list(chain[sent:int(hi)]),
                        },
                        [k, v],
                        timeout=30.0,
                    )
                except Exception as e:
                    logger.debug("kv replication push failed: %s", e)
                    return False
                installed = (
                    int(reply.get("installed", 0))
                    if isinstance(reply, dict) else 0
                )
                if installed <= 0:
                    logger.info(
                        "standby %s:%d declined kv_put (%s); stopping "
                        "replication for session %s", standby[0], standby[1],
                        (reply or {}).get("reason", "?"), session.id,
                    )
                    session.repl_standby = None
                    return False
                sent_by_row[row] = int(hi)
                self.repl_pages_sent += n
                progress = True
        return progress

    def _repl_lag(self) -> int:
        """Gauge: sealed-but-unshipped pages across replicating sessions
        (bounds how much a failover would replay beyond the unsealed
        tail)."""
        lag = 0
        for s in self._sessions.values():
            if not s.repl_chains or s.repl_sent is None:
                continue
            for row, chain in enumerate(s.repl_chains):
                if row < len(s.repl_sent):
                    lag += max(0, len(chain) - s.repl_sent[row])
        return lag

    # ---------------------------------------- compile-artifact replication
    def _artifact_fp(self) -> dict:
        """This server's artifact-compatibility fingerprint (jax/jaxlib
        version, backend, topology, model spec hash, span, compute dtype,
        KV page geometry). Installing past a mismatch could at best be a
        silent cache miss and at worst a refused deserialize — so both
        ends check it and decline."""
        return artifacts.fingerprint(
            self.spec, self.start_block, self.end_block,
            name_for_dtype(self.compute_dtype), self.manager.page_size,
        )

    def _note_artifact_fallback(self, reason: str) -> None:
        """Every path that abandons pre-installed artifacts funnels here:
        counted, ledgered (the chaos gate requires the fallback path
        actually ran when faulted), and loud. The fallback itself is
        plain local compile — always correct, never a crash."""
        self.artifact_fallback_compiles += 1
        ledger.recovery("server.artifact_fallback_compile")
        logger.warning(
            "compile-artifact fallback: %s; warmup will compile locally",
            reason,
        )

    async def _artifact_get(self, meta: dict, tensors):
        """Serving side of the swarm-shared compile-artifact cache:
        {"manifest": True} returns the digest-stamped blob listing plus
        our fingerprint; {"name": ...} returns one blob (as a uint8
        tensor). Declines with a reason instead of erroring, mirroring
        kv_put; the "artifact" meta stamp marks these frames for the
        chaos harness's artifact-stream fault predicates."""
        store = self.artifact_store
        if store is None:
            return {"artifact": True, "reason": "no artifact store"}, []
        if self._draining or self._crashed:
            return {"artifact": True, "reason": "draining"}, []
        if meta.get("manifest"):
            self.artifact_gets_served += 1
            return {
                "artifact": True,
                "manifest": store.manifest(),
                "fp": self._artifact_fp(),
            }, []
        name = str(meta.get("name") or "")
        blob = store.read_blob(name)
        if blob is None:
            return {"artifact": True, "reason": f"unknown artifact {name!r}"}, []
        self.artifact_gets_served += 1
        return {
            "artifact": True,
            "name": name,
            "digest": artifacts.blob_digest(blob),
        }, [np.frombuffer(blob, dtype=np.uint8)]

    async def _artifact_put(self, meta: dict, tensors):
        """Standby side of artifact replication: install one pushed blob
        into the local store, digest- and fingerprint-checked. Declines
        (installed=0 + reason) instead of erroring so mixed swarms — and
        corrupt or incompatible pushes — degrade to local compile."""
        store = self.artifact_store
        if store is None:
            return {
                "artifact": True, "installed": 0,
                "reason": "no artifact store",
            }, []
        if self._draining:
            return {"artifact": True, "installed": 0,
                    "reason": "draining"}, []
        mismatch = artifacts.fingerprint_compatible(
            self._artifact_fp(), dict(meta.get("fp") or {})
        )
        if mismatch is not None:
            self.artifact_puts_declined += 1
            return {
                "artifact": True, "installed": 0,
                "reason": f"fingerprint mismatch: {mismatch}",
            }, []
        if len(tensors) != 1:
            return {"artifact": True, "installed": 0,
                    "reason": "malformed payload"}, []
        blob = np.asarray(tensors[0], dtype=np.uint8).tobytes()
        decline = store.install(
            str(meta.get("name") or ""), blob, str(meta.get("digest") or "")
        )
        if decline is not None:
            self.artifact_puts_declined += 1
            return {"artifact": True, "installed": 0, "reason": decline}, []
        self.artifact_puts_installed += 1
        return {"artifact": True, "installed": 1}, []

    async def prefetch_artifacts(self) -> bool:
        """JOIN/standby-side fetch: pull this span's compile artifacts
        from covering ONLINE peers before warmup, so warmup loads
        executables instead of compiling them. Fault-tolerant by
        construction: a dead/declining peer retries on the next covering
        peer with the remaining blob set; a corrupt blob (manifest-digest
        mismatch) is declined and dropped; ANY shortfall — no peers, no
        manifest, declined or unfetched blobs — falls back to local
        compile, ledgered. Only a complete install marks the run
        pre-installed (a partial install would turn the jitwatch
        pre-installed gate red on the missing buckets, and rightly so).
        Never raises."""
        store = self.artifact_store
        if store is None or self.registry is None:
            return False
        timeout = float(env.get("BBTPU_ARTIFACT_FETCH_TIMEOUT_S"))
        my_fp = self._artifact_fp()
        try:
            infos = await self.registry.get_module_infos(
                self.model_uid, range(self.start_block, self.end_block)
            )
        except Exception as e:
            self._note_artifact_fallback(
                f"registry fetch failed: {e.__class__.__name__}"
            )
            return False
        peers: dict[tuple[str, int], None] = {}
        for info in infos or []:
            for sid, s in (info.servers if info else {}).items():
                if (
                    sid != self.server_id
                    and getattr(s, "artifacts", False)
                    and s.state == ServerState.ONLINE
                    and s.start_block <= self.start_block
                    and s.end_block >= self.end_block
                ):
                    peers.setdefault((str(s.host), int(s.port)))
        if not peers:
            self._note_artifact_fallback("no covering peer with artifacts")
            return False
        pending: dict[str, str] | None = None  # name -> manifest digest
        declined = 0
        installed = 0
        for i, addr in enumerate(peers):
            if i:
                self.artifact_fetch_retries += 1
            try:
                conn = await self.peers.get(*addr)
                reply, _ = await conn.call(
                    "artifact_get", {"artifact": True, "manifest": True},
                    [], timeout=timeout,
                )
                if not isinstance(reply, dict) or reply.get("reason"):
                    continue
                mismatch = artifacts.fingerprint_compatible(
                    my_fp, dict(reply.get("fp") or {})
                )
                if mismatch is not None:
                    logger.info(
                        "peer %s:%d artifact fingerprint mismatch (%s); "
                        "trying next peer", addr[0], addr[1], mismatch,
                    )
                    continue
                if pending is None:
                    pending = {
                        str(e["name"]): str(e["digest"])
                        for e in (reply.get("manifest") or [])
                        if isinstance(e, dict) and e.get("name")
                    }
                for name in list(pending):
                    r2, blobs = await conn.call(
                        "artifact_get", {"artifact": True, "name": name},
                        [], timeout=timeout,
                    )
                    if (
                        not isinstance(r2, dict) or r2.get("reason")
                        or len(blobs) != 1
                    ):
                        declined += 1
                        pending.pop(name)
                        continue
                    blob = np.asarray(blobs[0], dtype=np.uint8).tobytes()
                    # verify against the MANIFEST digest, not the one
                    # riding the blob reply: the manifest fetch is the
                    # trust anchor, so a blob corrupted in flight can't
                    # vouch for itself
                    why = store.install(name, blob, pending[name])
                    if why is not None:
                        declined += 1
                        logger.warning(
                            "artifact %s declined: %s", name, why
                        )
                    else:
                        installed += 1
                        self.artifact_blobs_fetched += 1
                    pending.pop(name)
                if not pending:
                    break
            except Exception as e:
                # peer death mid-fetch: the remaining pending set retries
                # verbatim on the next covering peer
                logger.warning(
                    "artifact fetch from %s:%d failed mid-stream: %s",
                    addr[0], addr[1], e,
                )
                continue
        if pending is None:
            self._note_artifact_fallback("no usable manifest from any peer")
            return False
        if declined or pending:
            self._note_artifact_fallback(
                f"{declined} blob(s) declined, {len(pending)} unfetched"
            )
            return False
        if not installed:
            self._note_artifact_fallback("peer manifest was empty")
            return False
        self._artifacts_preinstalled = True
        jitwatch.mark_preinstalled()
        logger.info(
            "pre-installed %d compile artifact(s); warmup will load, "
            "not compile", installed,
        )
        return True

    async def _push_artifacts(self, standby: tuple[str, int]) -> None:
        """Primary side: best-effort ship of the artifact store to a
        replication standby (bounded by _repl_sem so artifact traffic
        never crowds out live inference, same as KV sweeps). A decline
        stops the push; any failure just leaves the standby to prefetch
        at its own next warmup."""
        store = self.artifact_store
        if store is None:
            return
        fp = self._artifact_fp()
        try:
            for entry in store.manifest():
                blob = store.read_blob(entry["name"])
                if blob is None:
                    continue  # evicted mid-push
                async with self._repl_sem:
                    conn = await self.peers.get(*standby)
                    reply, _ = await conn.call(
                        "artifact_put",
                        {
                            "artifact": True,
                            "name": entry["name"],
                            "digest": entry["digest"],
                            "fp": fp,
                        },
                        [np.frombuffer(blob, dtype=np.uint8)],
                        timeout=30.0,
                    )
                if not (isinstance(reply, dict) and reply.get("installed")):
                    logger.info(
                        "standby %s:%d declined artifact_put (%s); "
                        "stopping artifact push", standby[0], standby[1],
                        (reply or {}).get("reason", "?"),
                    )
                    return
        except Exception as e:
            logger.debug(
                "artifact push to %s:%d failed: %s", standby[0],
                standby[1], e,
            )

    async def _rpc_inference(self, stream: Stream) -> None:
        """One decode session. Open meta: {session_id, batch_size, max_length,
        start?, end?}; items: {step, commit, reply, route} + [hidden (B,T,D)]
        (+ tree mask u8 [B,T,T] when meta['tree'])."""
        meta = stream.open_meta
        if self._draining:
            # routing should already avoid us (DRAINING announce), but a
            # client racing a stale swarm view can still arrive — refuse
            # before allocating KV it could never finish using
            raise RuntimeError("server is draining; open a session elsewhere")
        if self._standby:
            # a standby (or a replica mid-drain-back) holds weights and
            # replicated KV but is NOT serving: it announces JOINING, so
            # only a client racing a stale swarm view lands here
            raise RuntimeError(
                "server is a standby for this span; open a session on a "
                "serving replica"
            )
        if meta.get("resume") is not None:
            # reconnect-resume: re-attach a parked session instead of
            # allocating anything — this handler only hands its fresh
            # stream to the surviving page-owning handler
            await self._rpc_resume(stream, str(meta["resume"]))
            return
        session_id = meta["session_id"]
        batch = int(meta["batch_size"])
        max_length = int(meta["max_length"])
        adapter = meta.get("adapter")
        client_id = str(meta.get("client_id") or session_id)
        if self.admission is not None:
            # admission check BEFORE allocating KV: a session open is new
            # work by definition. Shedding here (structured, retriable)
            # beats admitting a session whose steps would then rot in the
            # queue until the client's deadline aborts them.
            retry_ms = self.admission.admit_new(
                client_id, self.compute.current_delay_ms(
                    self.admission.window_s
                ),
            )
            if retry_ms is not None:
                self.admission.shed_sessions += 1
                raise OverloadedError(
                    "server overloaded: queue delay past admission high "
                    "watermark; retry elsewhere",
                    retry_after_ms=retry_ms,
                )
        from bloombee_tpu.models.checkpoint import resolve_adapter

        resolve_adapter(self.adapter_factors, adapter)  # loud on unknown
        layers = self._resolve_layers(meta)
        async with self.manager.allocate(
            batch, max_length, timeout=self.alloc_timeout
        ) as handle:

            session = _Session(session_id, handle, batch, layers, adapter,
                               client_id=client_id)
            session.opened_at = clock.monotonic()
            session.last_step_at = session.opened_at
            self._sessions[session_id] = session
            self._drain_pending_pushes(session)
            cur_stream = stream
            try:
                while True:
                    session.cur_stream = cur_stream
                    try:
                        await self._session_loop(session, cur_stream)
                        break  # client half-closed: done
                    except (ConnectionClosed, OSError, RpcError) as e:
                        # the stream died under the session. With leases on
                        # (and KV not run ahead of the client's history),
                        # park and wait for a reconnect instead of freeing
                        if (
                            self.session_lease_s <= 0
                            or self._draining
                            or session.kv_dirty
                        ):
                            raise
                        cur_stream = await self._park_until_resumed(
                            session, e
                        )
                        if cur_stream is None:
                            break  # lease expired; pages reclaimed below
            finally:
                self._sessions.pop(session_id, None)
                if session.spec_drafted:
                    self._closed_session_spec[session_id] = {
                        "drafted": session.spec_drafted,
                        "accepted": session.spec_accepted,
                        "accept_rate": (
                            session.spec_accepted
                            / max(session.spec_drafted, 1)
                        ),
                    }
                    while len(self._closed_session_spec) > 64:
                        self._closed_session_spec.popitem(last=False)
                session.parked = False
                # release the resume handler carrying the current stream
                # (it returns once we are done with its stream)
                if session.detach_event is not None:
                    session.detach_event.set()
                    session.detach_event = None
                if cur_stream is not stream:
                    # the session ended on a RESUMED stream: its client is
                    # live and reading — half-close so it sees end-of-
                    # stream instead of hanging (the original stream's
                    # teardown runs in our caller, against a dead conn)
                    try:
                        await cur_stream.close()
                    except Exception:
                        pass
                if session.n_steps:
                    wall = clock.monotonic() - session.opened_at
                    logger.info(
                        "[TIMING_TABLE] session=%s steps=%d tokens=%d "
                        "mean_dispatch_ms=%.2f mean_fetch_ms=%.2f "
                        "wall_s=%.2f steps_per_s=%.2f",
                        session.id, session.n_steps, session.sum_tokens,
                        session.sum_dispatch_ms / session.n_steps,
                        session.sum_fetch_ms / session.n_steps,
                        wall, session.n_steps / max(wall, 1e-9),
                    )

    def _resolve_layers(self, meta: dict) -> tuple[int, int] | None:
        """Honor a requested sub-span (the router may enter this server's span
        mid-way: suffix sub-spans, reference spans_containing_block)."""
        start = int(meta.get("start", self.start_block))
        end = int(meta.get("end", self.end_block))
        if not (self.start_block <= start < end <= self.end_block):
            raise ValueError(
                f"requested blocks [{start},{end}) outside served span "
                f"[{self.start_block},{self.end_block})"
            )
        if (start, end) == (self.start_block, self.end_block):
            return None
        return (start - self.start_block, end - self.start_block)

    # ------------------------------------------- session leases & resume
    async def _park_until_resumed(
        self, session: _Session, cause: Exception
    ) -> Stream | None:
        """The session's stream died but its lease keeps it alive: drain
        in-flight work, hand the KV pages to the prefix pool as evictable
        cached entries (a parked session can never pin memory — under
        pressure its pages are simply evicted and the resume degrades to
        full replay), then sleep until a resume handler delivers a fresh
        stream or the reaper expires the lease. Returns the new stream, or
        None once the session is reclaimed."""

        # fence the dead stream: nothing may still be writing KV when the
        # pages change owner (same ordering as _session_loop teardown)
        if session.step_tasks:
            await asyncio.gather(*session.step_tasks, return_exceptions=True)
        if session.detach_event is not None:
            # the stream that just died was itself a resumed one — let its
            # carrier handler go
            session.detach_event.set()
            session.detach_event = None
        session.cur_stream = None
        session.resume_stream = None
        session.resume_waiter = asyncio.Event()
        session.lease_deadline = clock.monotonic() + self.session_lease_s
        session.parked = True
        await self.manager.lease_park(session.handle)
        ledger.recovery("server.lease_park")
        logger.info(
            "session %s parked after stream death (%s: %s); resumable for "
            "%.1fs", session.id, type(cause).__name__, cause,
            self.session_lease_s,
        )
        await session.resume_waiter.wait()
        session.parked = False
        if session.reaped or session.resume_stream is None:
            self.manager.lease_reclaim(session.handle)
            self.sessions_reaped += 1
            ledger.recovery("server.lease_reap")
            logger.info(
                "session %s lease expired while parked; KV reclaimed",
                session.id,
            )
            return None
        stream = session.resume_stream
        session.resume_stream = None
        session.lease_deadline = 0.0
        return stream

    async def _rpc_resume(self, stream: Stream, session_id: str) -> None:
        """Resume half of reconnect-resume: re-attach a parked session to
        this fresh stream. On success the PARKED handler (which owns the
        pages) serves the stream; this handler just holds the stream's RPC
        frame open until the session lets go of it. Declines (resumed:
        False) instead of erroring so the client cleanly falls back to the
        standby/full-replay path."""

        session = self._sessions.get(session_id)
        reason = None
        if session is None:
            reason = "unknown session (lease expired or never parked here)"
        elif session.kv_dirty:
            reason = "session KV ran ahead of acked history; replay"
        elif not session.parked:
            # the old stream looks alive from here (half-open not yet
            # detected): the client knows better — fence it and wait
            # briefly for the owner to park
            old = session.cur_stream
            if old is not None and old.conn is not stream.conn:
                old.conn.abort("superseded by session resume")
            for _ in range(100):
                if session.parked or session_id not in self._sessions:
                    break
                await clock.async_sleep(0.05)
            if not session.parked:
                reason = "session is still attached to a live stream"
        if reason is None and (
            session.reaped or clock.monotonic() >= session.lease_deadline
        ):
            reason = "session lease expired"
        if reason is None and not await self.manager.lease_resume(
            session.handle
        ):
            # parked pages were evicted under pressure (or the arena was
            # rebuilt): the copy is gone — expire the lease so the parked
            # handler reclaims instead of waiting out the clock
            reason = "parked KV no longer intact; replay"
            session.reaped = True
            session.resume_waiter.set()
        if reason is not None:
            logger.info(
                "refusing resume of session %s: %s", session_id, reason
            )
            await stream.send({"resumed": False, "reason": reason})
            return
        session.stream_epoch += 1
        detach = asyncio.Event()
        session.detach_event = detach
        session.resume_stream = stream
        self.sessions_resumed += 1
        logger.info(
            "session %s resumed on a fresh stream (epoch %d, last applied "
            "step %d)", session_id, session.stream_epoch,
            session.last_step_id,
        )
        # the ack carries the last APPLIED step id so the client
        # retransmits exactly its unacked tail (any retransmit of an
        # applied step dedups server-side anyway — belt and braces)
        await stream.send(
            {
                "resumed": True,
                "last_step": session.last_step_id,
                "epoch": session.stream_epoch,
            }
        )
        session.resume_waiter.set()
        await detach.wait()

    async def _lease_reaper_loop(self) -> None:
        """Background sweeper: expire parked sessions whose lease ran out,
        and fence live sessions whose client has been silent past the
        lease (belt and braces under keepalives; the only detector when
        keepalives are off). A fenced stream fails into the ordinary park
        path, so even this late detection hands the pages to the pool
        rather than freeing them under a client that might still return."""

        interval = max(0.05, self.session_lease_s / 4)
        while True:
            await clock.async_sleep(interval)
            now = clock.monotonic()
            for session in list(self._sessions.values()):
                if session.parked:
                    if now >= session.lease_deadline and not session.reaped:
                        session.reaped = True
                        if session.resume_waiter is not None:
                            session.resume_waiter.set()
                    continue
                stream = session.cur_stream
                conn = stream.conn if stream is not None else None
                # the lease renews on any applied step AND on any inbound
                # frame (keepalive pongs included): only a truly silent
                # client expires
                renewed = max(
                    session.last_step_at,
                    conn.last_recv if conn is not None else 0.0,
                )
                if conn is not None and now - renewed >= self.session_lease_s:
                    logger.warning(
                        "session %s silent for %.1fs (lease %.1fs): "
                        "fencing its stream", session.id, now - renewed,
                        self.session_lease_s,
                    )
                    conn.abort("session lease expired (silent client)")

    def _session_ages(self) -> dict:
        """Operator gauges for rpc_info: how old and how idle the live
        sessions are, and how many sit parked awaiting a resume."""

        now = clock.monotonic()
        ages = [now - s.opened_at for s in self._sessions.values()]
        idles = [now - s.last_step_at for s in self._sessions.values()]
        return {
            "sessions_parked": sum(
                1 for s in self._sessions.values() if s.parked
            ),
            "session_oldest_s": round(max(ages), 3) if ages else 0.0,
            "session_oldest_idle_s": round(max(idles), 3) if idles else 0.0,
        }

    def _dedup_step(self, session: _Session, meta: dict):
        """At-most-once: a step already applied (recorded reply) must not
        re-apply KV when the client retries it after a lost ack. Returns
        the recorded (resp_meta, tensors) to resend, or None for fresh
        work. Only consulted with leases on — without resume there are no
        retransmits to dedup."""
        step = meta.get("step")
        if self.session_lease_s <= 0 or step is None:
            return None
        step = int(step)
        if step < session.last_step_id:
            # long-superseded retransmit; the recorded replies are gone but
            # the client has also long since acted on newer steps — ack it
            return {"step": step, "ack": True, "deduped": True}, []
        return session.applied_steps.get((step, int(meta.get("mb") or 0)))

    def _record_reply(
        self, session: _Session, meta: dict, resp: dict, tensors: list
    ) -> None:
        """Record a step's reply BEFORE first delivery (the KV mutation is
        already applied by now): if the ack is lost to a dying stream, the
        client's post-resume retransmit gets this exact reply back instead
        of a second application. Only the latest step's replies are kept —
        the client's window never retries older ones."""
        step = meta.get("step")
        if self.session_lease_s <= 0 or step is None:
            return
        step = int(step)
        if step > session.last_step_id:
            session.last_step_id = step
            session.applied_steps.clear()
        session.applied_steps[(step, int(meta.get("mb") or 0))] = (
            resp, tensors,
        )

    async def _session_loop(self, session: _Session, stream: Stream) -> None:
        """Race client-stream items against pushed items
        (reference handler.py:1677-1847). Micro-batch chunks (mb_of > 1) run
        as concurrent tasks so chunk k+1's compute dispatches while chunk k's
        output is still in flight downstream — the within-stage overlap of
        the reference's accumulate/immediate queues (handler.py:1850-2151);
        whole-batch steps keep strict sequential handling."""
        stream_next = asyncio.ensure_future(stream.recv())
        push_next = asyncio.ensure_future(session.push_inbox.get())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {stream_next, push_next},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if stream_next in done:
                    item = stream_next.result()
                    if item is None:
                        break  # client closed the session
                    await self._handle_item(session, stream, *item)
                    stream_next = asyncio.ensure_future(stream.recv())
                if push_next in done:
                    meta, tensors = push_next.result()
                    await self._handle_item(session, stream, meta, tensors)
                    push_next = asyncio.ensure_future(session.push_inbox.get())
        finally:
            stream_next.cancel()
            if push_next.done() and not push_next.cancelled():
                # the race was lost at teardown: push_inbox.get() completed
                # with an item nobody consumed. Cancelling would silently
                # drop a pushed micro-batch chunk — requeue it instead so a
                # parked session's resume (or the pending-push buffer path)
                # still sees it, and count it for operators
                try:
                    session.push_inbox.put_nowait(push_next.result())
                    self.pushes_dropped += 1  # requeued, but the loop ended
                    logger.info(
                        "session %s teardown requeued an unconsumed pushed "
                        "item (%d total across sessions)", session.id,
                        self.pushes_dropped,
                    )
                except Exception:
                    # the push either failed in flight or the inbox is
                    # full — both moot at teardown; the client replays
                    pass
            else:
                push_next.cancel()
            # drain in-flight chunk tasks BEFORE the allocate context frees
            # the session's pages: a still-running dispatch must not write
            # KV into pages a new session may reuse
            if session.step_tasks:
                await asyncio.gather(
                    *session.step_tasks, return_exceptions=True
                )

    async def _handle_item(
        self, session: _Session, stream: Stream, meta: dict, tensors: list
    ) -> None:
        if int(meta.get("mb_of", 1)) <= 1:
            await self._run_step(session, stream, meta, tensors)
            return
        task = asyncio.create_task(
            self._run_step_logged(session, stream, meta, tensors)
        )
        session.step_tasks.add(task)
        task.add_done_callback(session.step_tasks.discard)

    async def _run_step_logged(
        self, session: _Session, stream: Stream, meta: dict, tensors: list
    ) -> None:
        try:
            await self._run_step(session, stream, meta, tensors)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # a failed chunk poisons the whole step: close the stream so the
            # client's retry path rebuilds the chain
            logger.warning("micro-batch step failed: %s", e)
            try:
                await stream.close()
            except Exception:
                pass

    async def _maybe_reply_session_lost(
        self, session: _Session, stream: Stream, meta: dict, e: Exception
    ) -> bool:
        """Classify a step failure: when this session's KV is gone (arena
        rebuilt, or a parked copy lost), reply the typed `session_lost` so
        the client replays WITHOUT banning the healthy server (advisor,
        round 4). Covers both the step that finds a stale epoch and the
        step whose own failure consumed the arena (the executor rebuilds
        before re-raising, so the epoch is stale by reply time)."""
        if isinstance(
            e, (SessionKVLost, ParkedKVLost)
        ) or not self.manager.epoch_valid(session.handle):
            await stream.send(
                {
                    "step": meta.get("step"),
                    "session_lost": True,
                    "reason": str(e),
                }
            )
            return True
        return False

    @staticmethod
    def _local_deadline(meta: dict) -> float | None:
        """meta['deadline_s'] (relative remaining seconds stamped by the
        client or shrunk by the previous hop) -> local monotonic cutoff,
        or None when the item carries no budget."""

        budget = meta.get("deadline_s")
        if budget is None:
            return None
        return clock.monotonic() + float(budget)

    @staticmethod
    def _deadline_passed(deadline: float | None) -> bool:

        return deadline is not None and clock.monotonic() > deadline

    def _liar_perturb(self, out: np.ndarray) -> np.ndarray:
        """TEST HOOK (liar_p): return a perturbed copy of a span output —
        the Byzantine lie the client integrity layer exists to convict.
        Deliberately LOUD (NaN poison / x64 scale / exponent bit-flip):
        the point is exercising detection+quarantine end to end, not
        probing the envelope's sensitivity floor."""
        arr = np.array(out, copy=True)
        if arr.size == 0:
            return out
        mode = ("nan", "scale", "bitflip")[self._liar_rng.randrange(3)]
        flat = arr.reshape(-1)
        idx = self._liar_rng.randrange(flat.size)
        if mode == "nan":
            flat[idx] = float("nan")
        elif mode == "scale":
            np.multiply(arr, arr.dtype.type(64), out=arr)
        else:
            view = flat.view(np.uint8)
            byte = idx * arr.dtype.itemsize + (arr.dtype.itemsize - 1)
            view[byte] ^= 0x40
        return arr

    def _note_deadline_expired(self, meta: dict, where: str) -> None:
        self.deadlines_expired += 1
        logger.info(
            "dropping step %s: client deadline expired %s "
            "(%d drops total)", meta.get("step"), where,
            self.deadlines_expired,
        )

    async def _run_step(
        self, session: _Session, stream: Stream, meta: dict, tensors: list
    ) -> None:
        if meta.get("chain") is not None:
            # pushed hop of a chained decode_n (never from the client
            # stream): errors go back to the coordinator via chain_error,
            # not to our own client's stream
            await self._run_chain_step(session, meta, tensors)
            return
        repl = meta.get("kv_repl")
        if repl is not None:
            # async session-KV replication control: record the standby +
            # the client's full-history hash chains, publish our own
            # sealed decode pages locally under those hashes, and schedule
            # shipping the backlog. Fire-and-forget: NO reply (a reply
            # would desync the client's strictly-ordered step stream).
            self._note_kv_repl(session, repl)
            return
        cached = self._dedup_step(session, meta)
        if cached is not None:
            # at-most-once: this step was already applied and its reply
            # recorded before the stream died — resend the identical reply
            # instead of mutating KV a second time
            self.steps_deduped += 1
            ledger.recovery("server.resume_dedup")
            resp, out_t = cached
            await stream.send({**resp, "deduped": True}, out_t)
            return
        # client deadline budget: "deadline_s" is RELATIVE remaining time
        # (never an absolute timestamp — clocks differ across machines);
        # convert to a local monotonic cutoff at arrival
        deadline = self._local_deadline(meta)
        if self._deadline_passed(deadline):
            self._note_deadline_expired(meta, "on arrival")
            return
        if not self.manager.epoch_valid(session.handle):
            # cheap pre-check so a stale session's accept/decode never
            # touches zeroed table state (authoritative check re-runs on
            # the compute thread, racing rebuilds are classified below)
            await stream.send(
                {
                    "step": meta.get("step"),
                    "session_lost": True,
                    "reason": "server KV arena was rebuilt; session cache "
                    "lost — replay",
                }
            )
            return
        probe = meta.get("prefix_probe")
        if probe is not None:
            # prefix-cache probe: adopt each row's longest pooled prompt
            # prefix NOW (refcount-pinning the pages against eviction) and
            # report the per-row hit; the client follows up with the
            # chain-wide skip on its prefill. Pure host-side table work —
            # no reason to wait behind the compute queue.
            matched = self.manager.adopt_prefix(session.handle, probe)
            resp = {"step": meta.get("step"), "prefix_matched": matched}
            self._record_reply(session, meta, resp, [])
            await stream.send(resp)
            return
        # speculative accept from the previous round: compact surviving KV
        # rows onto the committed prefix before this step's compute
        accept = meta.get("accept")
        if accept is not None:
            if session.last_tree is not None:
                # background: training must never stall the event loop or
                # delay this accept's own step
                task = asyncio.create_task(
                    self._train_pruner_head(session, accept)
                )
                session.step_tasks.add(task)
                task.add_done_callback(session.step_tasks.discard)
            try:
                await self.compute.submit(
                    PRIORITY_INFERENCE,
                    self.manager.accept_speculative,
                    session.handle,
                    [np.asarray(a, dtype=np.int64) for a in accept],
                )
            except Exception as e:
                if await self._maybe_reply_session_lost(
                    session, stream, meta, e
                ):
                    return
                raise
            # measured speculation: each row's accept keeps its surviving
            # path beyond node 0 (node 0 is the previous round's bonus
            # token — certain, not drafted)
            kept = sum(max(0, len(a) - 1) for a in accept)
            self.spec_tokens_accepted += kept
            session.spec_accepted += kept
        if meta.get("accept_only"):
            # the accept above compacted KV: record before delivery so a
            # retried accept after a lost ack never compacts twice
            resp = {"step": meta.get("step"), "ack": True}
            self._record_reply(session, meta, resp, [])
            await stream.send(resp)
            return
        if meta.get("decode_n"):
            await self._run_decode_n(session, stream, meta, tensors)
            return

        if self.admission is not None and session.n_steps == 0:
            # in-stream shed for NEW work only: a session that has never
            # completed a step is about to run its prefill — if overload
            # began after its open was admitted, refuse it now with the
            # typed retriable reply (mirrors session_lost) instead of
            # queueing it. A session with n_steps > 0 is ESTABLISHED: its
            # next decode step is always admitted, so live streams degrade
            # gracefully rather than die.
            retry_ms = self.admission.admit_new(
                session.client_id, self.compute.current_delay_ms(
                    self.admission.window_s
                ),
            )
            if retry_ms is not None:
                await stream.send({
                    "step": meta.get("step"),
                    "overloaded": True,
                    "retry_after_ms": retry_ms,
                    "reason": "server overloaded: new-session prefill shed "
                    "past admission high watermark",
                })
                return

        # keep the sender's dtype (bf16 on the production wire); the executor
        # casts to compute dtype on device
        hidden = np.asarray(tensors[0])
        tree_mask = None
        depths = None
        # kind-aware group_hint gauge: tree steps mark the session
        # speculating (spec-decode rounds are all tree steps, so the flag
        # is stable between rounds); a plain single-token decode step
        # reveals a NON-speculating session. Prefill / chunk steps are
        # kind-neutral — the session might start speculating right after
        # its prompt, so they leave the optimistic default alone.
        if meta.get("tree"):
            session.speculating = True
        elif hidden.shape[1] == 1:
            session.speculating = False
        if meta.get("tree"):
            tree_mask = np.asarray(tensors[1], dtype=bool)
            if meta.get("depths") is not None:
                depths = np.asarray(meta["depths"], dtype=np.int32)
            # spec-decode observability: every tree-verify step counts
            # (solo or grouped); node 0 of each row is the previous bonus
            # token, so drafted = rows * (nodes - 1)
            drafted = int(hidden.shape[0]) * max(0, int(hidden.shape[1]) - 1)
            self.tree_steps += 1
            self.tree_rows += int(hidden.shape[0])
            self.spec_tokens_drafted += drafted
            session.spec_drafted += drafted
        commit = bool(meta.get("commit", True))
        # micro-batch chunk: operate on a row slice of the session's cache
        # handle (seq_ids are independent, so a sub-handle is just a slice)
        rows = meta.get("rows")
        handle = session.handle
        if rows is not None and tuple(rows) != (0, session.batch_size):
            import dataclasses as _dc

            handle = _dc.replace(
                session.handle, seq_ids=session.handle.seq_ids[rows[0]:rows[1]]
            )
        if hidden.shape[0] != handle.batch_size:
            raise ValueError(
                f"step rows {rows} carry batch {hidden.shape[0]} != "
                f"{handle.batch_size} cache rows"
            )

        # Two phases: dispatch runs on the serialized compute queue (device
        # work enqueues in order, ~1 ms), but the d2h fetch happens HERE, off
        # the queue, so concurrent sessions overlap their device round trips
        # (the round trip dominates per-step latency on tunnel/DCN hosts —
        # the reference overlaps the same way with per-handler processes and
        # CUDA streams, task_pool.py:127-192).
        # ragged replay: the step writes a padded rectangle speculatively
        # and each row commits to its true length (freeing the padding's
        # pages) INSIDE the same compute-thread slot as the dispatch, so an
        # over-subscribed reclaimer can never park the session in between.
        # `handle` may be a row slice — align lengths to its rows.
        commit_lens = meta.get("commit_lens")
        if commit_lens is not None:
            commit_lens = [int(x) for x in commit_lens]
            if rows is not None:
                commit_lens = commit_lens[rows[0]:rows[1]]
        try:
            if self._batchable(commit, hidden, tree_mask, depths,
                               commit_lens, meta.get("prefix_skip")):
                # continuous batching: compatible single-token decode steps
                # of OTHER sessions that are queued right now (or arrive
                # within BBTPU_BATCH_WINDOW_MS) share one merged span
                # dispatch; this call still returns only our own rows
                out_dev, t_dispatch_ms = await self.compute.submit_group(
                    PRIORITY_INFERENCE,
                    ("decode1", session.layers, session.adapter,
                     str(hidden.dtype)),
                    _BatchMember(session, handle, hidden),
                    # with --mixed-batch / --spec-batch the group may also
                    # hold a prefill chunk or tree-verify rows; the ragged
                    # runner degrades to the classic decode-group path for
                    # chunk-free, tree-free groups
                    self._compute_ragged_group
                    if (self.mixed_batch or self.spec_batch)
                    else self._compute_step_group,
                    deadline=deadline,
                    task_class="decode",
                )
            elif self._tree_batchable(commit, tree_mask, depths,
                                      commit_lens, meta):
                # batched tree verification: compatible tree-verify steps
                # of OTHER speculating sessions that are queued right now
                # (or arrive within BBTPU_BATCH_WINDOW_MS) pad/stack into
                # one ragged span dispatch; trees of differing size share
                # the key (size is not part of it), and with --mixed-batch
                # also on, the compat predicate fuses tree rows with
                # decode rows and a prefill chunk in the SAME dispatch
                out_dev, t_dispatch_ms = await self.compute.submit_group(
                    PRIORITY_INFERENCE,
                    ("tree", session.layers, session.adapter,
                     str(hidden.dtype)),
                    _TreeMember(session, handle, hidden, tree_mask, depths),
                    self._compute_ragged_group,
                    deadline=deadline,
                    task_class="decode",
                )
            else:
                spans = self._chunk_spans(
                    hidden, commit, tree_mask, commit_lens
                )
                if spans is not None:
                    # stall-free scheduling: the prefill becomes a stream
                    # of resumable chunk tasks re-entering the priority
                    # queue, so other sessions' decode steps run between
                    # chunks instead of stalling behind the whole prompt
                    out_dev, t_dispatch_ms = await self._run_chunked_prefill(
                        session, handle, hidden, spans, deadline,
                        meta.get("prefix_skip"),
                    )
                else:
                    is_prefill = hidden.shape[1] > 1 and tree_mask is None
                    out_dev, t_dispatch_ms = await self.compute.submit(
                        PRIORITY_INFERENCE,
                        self._compute_step,
                        session,
                        handle,
                        hidden,
                        commit,
                        tree_mask,
                        depths,
                        commit_lens,
                        meta.get("prefix_skip"),
                        deadline=deadline,
                        task_class="prefill" if is_prefill else "decode",
                    )
        except DeadlineExpired:
            self._note_deadline_expired(meta, "while queued")
            return
        except Exception as e:
            if await self._maybe_reply_session_lost(
                session, stream, meta, e
            ):
                return
            raise

        t0 = clock.perf_counter()
        out = await asyncio.to_thread(self.executor.fetch, out_dev)
        t_fetch_ms = (clock.perf_counter() - t0) * 1000.0
        if self.liar_p > 0 and self._liar_rng.random() < self.liar_p:
            # TEST HOOK: lie BEFORE the digest/serialization below, so the
            # reply is a well-formed frame whose digest matches the lie —
            # only the client's sanity gate / cross-replica audits can
            # catch it (exactly the threat model they exist for)
            out = self._liar_perturb(out)
            self.liar_steps += 1
        t_compute_ms = t_dispatch_ms + t_fetch_ms
        timing_meta = {
            "t_compute_ms": t_compute_ms,
            "t_dispatch_ms": t_dispatch_ms,
            "t_fetch_ms": t_fetch_ms,
        }
        session.n_steps += 1
        session.sum_tokens += int(hidden.shape[0]) * int(hidden.shape[1])
        session.sum_dispatch_ms += t_dispatch_ms
        session.sum_fetch_ms += t_fetch_ms
        if self.admission is not None:
            # fair-share accounting: charge processed tokens (batch x seq)
            # to the owning client so heavy clients accrue debt
            self.admission.note_tokens(
                session.client_id,
                int(hidden.shape[0]) * int(hidden.shape[1]),
            )
        dump_dir = env.get("BBTPU_DUMP_ACTIVATIONS")
        if dump_dir:
            self._dump_activations(dump_dir, session, meta, hidden, out)

        # mid-chain tree pruning: score this span's output with the MidLMHead
        # and return only surviving rows + their indices (reference
        # backend.py:395-410 last-block prune, :763-775 flatten kept rows)
        keep = None
        prune = meta.get("prune")
        if prune is not None and tree_mask is not None:
            # first use loads the checkpoint's lm_head OFF the event loop
            # (a synchronous multi-GB safetensors read would stall every
            # session and the liveness announce)
            await self._ensure_pruner_loaded()
            keep = self._prune_tree(out, prune)
            if env.get("BBTPU_PRUNER_TRAIN"):
                # retain this tree's mid hidden; the accept that names the
                # full model's true path arrives with the NEXT step and
                # becomes the head's training signal
                session.last_tree = (
                    np.asarray(out, dtype=np.float32),
                    np.asarray(prune["tokens"], dtype=np.int64),
                    np.asarray(prune["parents"], dtype=np.int32),
                )
            if keep is not None:
                gather = np.where(keep >= 0, keep, 0)
                out = np.stack(
                    [out[i][gather[i]] for i in range(out.shape[0])]
                )

        route = meta.get("route") or []
        reply = meta.get("reply", "tensor")
        if route:
            nxt = route[0]
            push_meta = {
                "session_id": nxt["session_id"],
                "step": meta.get("step"),
                "commit": commit,
                "tree": meta.get("tree", False),
                "reply": reply,
                "route": route[1:],
            }
            for key in ("mb", "mb_of", "rows", "commit_lens", "prefix_skip"):
                if meta.get(key) is not None:
                    push_meta[key] = meta[key]
            if meta.get("tree"):
                push_meta["depths"] = meta["depths"]
            if accept is not None:
                push_meta["accept"] = accept
            if deadline is not None:
                # each hop spends part of the budget; forward the REMAINDER
                # so a downstream span never computes for a client whose
                # overall step timeout already fired
                remaining = deadline - clock.monotonic()
                if remaining <= 0:
                    self._note_deadline_expired(meta, "before forwarding")
                    return
                push_meta["deadline_s"] = remaining
            push_tensors = [out]  # executor output is already wire dtype
            if tree_mask is not None:
                push_tensors.append(tree_mask.astype(np.uint8))
            conn = await self.peers.get(nxt["host"], nxt["port"])
            async with self.peers.limiter(nxt["host"], nxt["port"]).slot():
                await conn.push("rpc_push", push_meta, push_tensors)
            # ack our own client stream so it can detect this hop succeeded
            # (recorded AFTER the downstream push: a resume-retried step
            # must re-push only if the push itself never happened)
            resp = {"step": meta.get("step"), "ack": True, **timing_meta}
            self._record_reply(session, meta, resp, [])
            await stream.send(resp)
        elif reply == "ack":
            resp = {"step": meta.get("step"), "ack": True, **timing_meta}
            self._record_reply(session, meta, resp, [])
            await stream.send(resp)
        else:
            resp = {"step": meta.get("step"), **timing_meta}
            for key in ("mb", "rows"):
                if meta.get(key) is not None:
                    resp[key] = meta[key]
            if keep is not None:
                resp["keep"] = keep.tolist()
            if self.integrity:
                # digest over the exact array we serialize next: integrity
                # clients recompute it on the deserialized chunk, so ANY
                # in-flight byte corruption is caught deterministically
                from bloombee_tpu.kv.prefix import out_digest

                resp["out_digest"] = out_digest(out)
                self.out_digests_sent += 1
            # record-then-send: the KV commit already happened at dispatch,
            # so this reply is the step's only at-most-once fence
            self._record_reply(session, meta, resp, [out])
            await stream.send(resp, [out])

    async def _run_decode_n(
        self, session: _Session, stream: Stream, meta: dict, tensors: list
    ) -> None:
        """Server-side multi-step greedy decode: one RPC returns N token
        ids, amortizing the client<->server round trip that floors served
        throughput. Three flavors, picked per request:

        - FUSED (route empty, dense un-sharded whole-model span): one
          jitted lax.scan runs embed -> span -> head -> select N times
          entirely on device (runtime/decode_loop.py) — one host<->device
          round trip for N tokens.
        - LOCAL STEPPED (route empty, whole-model span that the scan can't
          fuse: TP-sharded / quantized KV / weight-offloaded / hetero /
          sparse): the same loop driven per-step from the host through the
          ordinary executor paths. Still ONE client RTT per N tokens;
          per-step device round trips are local and cheap.
        - CHAINED (route non-empty): this span embeds + computes block 0's
          prefix and pushes hidden downstream; the LAST span applies
          norm+head+select and pushes the next id back here; this
          coordinator replies [B, n] ids after n rounds. The client RTT —
          the expensive tunnel/DCN hop — is paid once per N tokens; the
          per-token hops ride server-to-server links. This beats the
          reference's per-token client loop for the multi-server topology
          (remote_generation.py:286-386).

        An ineligible server replies decode_n_unsupported so the client
        falls back to per-step decoding without banning the peer."""
        n = int(meta["decode_n"])
        route = meta.get("route") or []
        decline = None
        if not (1 <= n <= self.decode_n_max):
            # unvalidated n would let one RPC eagerly commit n write_slots
            # per row (trivial OutOfPages) — clamp before any allocation
            decline = (
                f"decode_n={n} outside the server's accepted range "
                f"[1, {self.decode_n_max}]"
            )
        if decline is None:
            # every flavor embeds ids at this span, so the session must
            # enter the model at block 0 and the embed table must exist
            rel = session.layers or (0, self.end_block - self.start_block)
            if self.start_block + rel[0] != 0:
                decline = "session does not enter the model at block 0"
            elif (
                not route
                and self.start_block + rel[1] != self.spec.num_hidden_layers
            ):
                decline = (
                    "single-span decode_n needs the whole model on this "
                    "server (send a route for chained decode)"
                )
        if decline is None:
            await self._ensure_client_params()
            if self._client_params is None:
                decline = "server has no embed/norm/lm_head params"
        if decline is None:
            want_dt = meta.get("head_dtype")
            have_dt = str(self._client_params["lm_head"].dtype)
            if want_dt is not None and want_dt != have_dt:
                # client loaded its head with a dtype override; different
                # weights would yield different logits than its per-step path
                decline = (
                    f"head dtype mismatch: client {want_dt} vs server "
                    f"{have_dt}"
                )
        if decline is not None:
            # the reason rides the reply so an operator can see WHY a
            # client fell back to per-step decoding (a silent decline loses
            # the whole feature invisibly — round-3 verdict)
            logger.warning("decode_n declined: %s", decline)
            await stream.send(
                {
                    "step": meta.get("step"),
                    "decode_n_unsupported": True,
                    "reason": decline,
                }
            )
            return
        if route or self._decode_n_ineligible(session) is not None:
            await self._run_decode_n_stepped(
                session, stream, meta, tensors, route
            )
            return
        await self._run_decode_n_fused(session, stream, meta, tensors)

    async def _run_decode_n_fused(
        self, session: _Session, stream: Stream, meta: dict, tensors: list
    ) -> None:
        n = int(meta["decode_n"])
        ids = np.asarray(tensors[0]).reshape(-1)
        if ids.shape[0] != session.handle.batch_size:
            raise ValueError(
                f"decode_n ids carry batch {ids.shape[0]} != "
                f"{session.handle.batch_size} cache rows"
            )
        eos = meta.get("eos_token_id")
        finished = (
            np.asarray(meta["finished"], dtype=bool)
            if meta.get("finished") is not None else None
        )

        def _dispatch():
            if not self.manager.epoch_valid(session.handle):
                raise SessionKVLost(
                    "server KV arena was rebuilt; session cache lost — "
                    "replay"
                )
            session.last_step_at = clock.monotonic()
            t0 = clock.perf_counter()
            out = self.executor.decode_n(
                session.handle, ids, n, self._client_params,
                eos_token_id=eos, finished=finished,
                adapter=session.adapter,
            )
            return out, (clock.perf_counter() - t0) * 1000.0

        try:
            out_dev, t_dispatch_ms = await self.compute.submit(
                PRIORITY_INFERENCE, _dispatch,
                deadline=self._local_deadline(meta),
            )
        except DeadlineExpired:
            self._note_deadline_expired(meta, "while queued")
            return
        except Exception as e:
            if await self._maybe_reply_session_lost(
                session, stream, meta, e
            ):
                return
            raise
        t0 = clock.perf_counter()
        toks = await asyncio.to_thread(
            lambda: np.asarray(out_dev, dtype=np.int32)
        )
        t_fetch_ms = (clock.perf_counter() - t0) * 1000.0
        session.n_steps += n
        session.sum_tokens += int(ids.shape[0]) * n
        session.sum_dispatch_ms += t_dispatch_ms
        session.sum_fetch_ms += t_fetch_ms
        if self.admission is not None:
            self.admission.note_tokens(
                session.client_id, int(ids.shape[0]) * n
            )
        resp = {
            "step": meta.get("step"),
            "t_compute_ms": t_dispatch_ms + t_fetch_ms,
            "t_dispatch_ms": t_dispatch_ms,
            "t_fetch_ms": t_fetch_ms,
        }
        # the fused loop committed n KV slots per row: record before
        # delivery so a post-resume retry resends these exact tokens
        # instead of decoding (and committing) n more
        self._record_reply(session, meta, resp, [toks])
        await stream.send(resp, [toks])

    async def _run_decode_n_stepped(
        self, session: _Session, stream: Stream, meta: dict, tensors: list,
        route: list,
    ) -> None:
        """Host-driven decode_n loop (the LOCAL STEPPED and CHAINED flavors
        of _run_decode_n). Each round: embed the current ids, run this
        span's ordinary per-step executor path, then either apply the head
        locally (empty route) or push hidden downstream and await the tail
        span's selected ids. EOS masking happens HERE, identically to the
        client's per-step loop (_greedy_next), so outputs are token-exact
        vs per-step decoding on the same backend.

        Failure contract: once any KV was committed this RPC, spans hold
        ragged extra tokens — the decline carries dirty=True so the client
        rebuilds-and-replays before falling back (clean by construction)."""

        n = int(meta["decode_n"])
        ids = np.asarray(tensors[0]).reshape(-1).astype(np.int64)
        if ids.shape[0] != session.handle.batch_size:
            raise ValueError(
                f"decode_n ids carry batch {ids.shape[0]} != "
                f"{session.handle.batch_size} cache rows"
            )
        b = int(ids.shape[0])
        eos = meta.get("eos_token_id")
        finished = (
            np.asarray(meta["finished"], dtype=bool)
            if meta.get("finished") is not None
            else np.zeros((b,), dtype=bool)
        )
        cid = uuid.uuid4().hex[:12]
        # drop stale control messages from an earlier timed-out chain
        while not session.chain_inbox.empty():
            session.chain_inbox.get_nowait()
        toks = np.zeros((b, n), dtype=np.int32)
        committed = 0
        t_start = clock.perf_counter()
        t_dispatch_sum = 0.0
        # total budget for the WHOLE chain RPC: one cold-compile allowance
        # plus 1s/token. Deliberately under the client's recv budget
        # (2*step_timeout + n): the server must always answer — a typed
        # transient decline beats the client timing out and BANNING a
        # coordinator that was making slow-but-legal progress. A retry
        # after replay hits warm compile caches and converges.
        t_deadline = clock.monotonic() + self.chain_step_timeout + float(n)
        budget = meta.get("deadline_s")
        if budget is not None:
            # never outlive the CLIENT's budget either: past it the reply
            # lands on a closed ear and every further token is waste
            t_deadline = min(t_deadline, clock.monotonic() + float(budget))
        try:
            for i in range(n):
                if clock.monotonic() > t_deadline:
                    raise _ChainError(
                        f"chain exceeded its {self.chain_step_timeout:.0f}s"
                        f"+{n}s budget after {i}/{n} tokens"
                    )
                def _dispatch(ids_now=ids):
                    if not self.manager.epoch_valid(session.handle):
                        raise SessionKVLost(
                            "server KV arena was rebuilt; session cache "
                            "lost — replay"
                        )
                    session.last_step_at = clock.monotonic()
                    t0 = clock.perf_counter()
                    h = self._embed_ids(ids_now)
                    out = self.executor.decode(
                        session.handle,
                        h.astype(self.executor.transfer_dtype),
                        commit=True, layers=session.layers, fetch=False,
                        adapter=session.adapter,
                    )
                    return out, (clock.perf_counter() - t0) * 1000.0
                out_dev, dt_ms = await self.compute.submit(
                    PRIORITY_INFERENCE, _dispatch
                )
                committed += 1
                t_dispatch_sum += dt_ms
                if route:
                    out = await asyncio.to_thread(self.executor.fetch, out_dev)
                    chain = {
                        "origin": {
                            "host": self.public_host,
                            "port": self.port,
                            "session_id": session.id,
                        },
                        "cid": cid,
                        "i": i,
                    }
                    await self._push_hop(
                        route, chain, meta.get("step"),
                        meta.get("head_dtype"), out,
                        deadline_s=t_deadline - clock.monotonic(),
                    )
                    nxt = await self._await_chain_ids(
                        session, cid, i, t_deadline
                    )
                else:
                    nxt = await self.compute.submit(
                        PRIORITY_INFERENCE, self._select_head, out_dev
                    )
                # EOS masking: one definition with the client's per-step
                # loop semantics (client/model.py _mask_finished)
                if eos is not None:
                    nxt = np.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                toks[:, i] = nxt
                ids = nxt.astype(np.int64)
        except Exception as e:
            # committed KV the client was never told about makes a parked
            # resume unsound (token histories would diverge): if the dirty
            # decline below cannot be delivered, the park path sees
            # kv_dirty and falls back to full replay. Delivering it
            # clears the flag — the client then rebuilds explicitly.
            session.kv_dirty = committed > 0
            if await self._maybe_reply_session_lost(
                session, stream, meta, e
            ):
                session.kv_dirty = False
                return
            logger.warning(
                "chained decode_n failed after %d/%d committed steps: %s",
                committed, n, e,
            )
            await stream.send(
                {
                    "step": meta.get("step"),
                    "decode_n_unsupported": True,
                    "reason": f"{type(e).__name__}: {e}",
                    # committed KV ran ahead of the client's history: the
                    # client must rebuild-and-replay before continuing
                    "dirty": committed > 0,
                    # transient route failures (a span died) are worth a
                    # rebuild-and-RETRY of chained decode; capability
                    # declines are not
                    "transient": not getattr(e, "permanent", False),
                }
            )
            # the decline reached the client: it rebuilds-and-replays, so
            # the ragged KV no longer blocks a later park
            session.kv_dirty = False
            return
        total_ms = (clock.perf_counter() - t_start) * 1000.0
        session.n_steps += n
        session.sum_tokens += b * n
        session.sum_dispatch_ms += t_dispatch_sum
        session.sum_fetch_ms += max(total_ms - t_dispatch_sum, 0.0)
        if self.admission is not None:
            self.admission.note_tokens(session.client_id, b * n)
        resp = {
            "step": meta.get("step"),
            "t_compute_ms": total_ms,
            "t_dispatch_ms": t_dispatch_sum,
            "t_fetch_ms": max(total_ms - t_dispatch_sum, 0.0),
        }
        self._record_reply(session, meta, resp, [toks])
        await stream.send(resp, [toks])

    async def _push_hop(
        self, route: list, chain: dict, step, head_dtype, out,
        deadline_s: float | None = None,
    ) -> None:
        """Push one chained-decode hidden state to the next hop (shared by
        the coordinator and middle spans — the hop wire format lives in
        exactly one place)."""
        nxt_hop = route[0]
        push_meta = {
            "session_id": nxt_hop["session_id"],
            "step": step,
            "commit": True,
            "chain": chain,
            "route": route[1:],
        }
        if head_dtype is not None:
            push_meta["head_dtype"] = head_dtype
        if deadline_s is not None:
            push_meta["deadline_s"] = deadline_s
        conn = await self.peers.get(nxt_hop["host"], nxt_hop["port"])
        async with self.peers.limiter(
            nxt_hop["host"], nxt_hop["port"]
        ).slot():
            await conn.push("rpc_push", push_meta, [out])

    async def _await_chain_ids(
        self, session: _Session, cid: str, i: int, t_deadline: float
    ) -> np.ndarray:
        """Wait for the tail span's selected ids for chain step (cid, i);
        stale messages from earlier chains are dropped, errors raise.
        Bounded by the chain's overall deadline so the RPC always answers
        inside the client's recv budget."""

        while True:
            remaining = t_deadline - clock.monotonic()
            if remaining <= 0:
                raise asyncio.TimeoutError("chain deadline exhausted")
            msg_meta, msg_tensors = await asyncio.wait_for(
                session.chain_inbox.get(), remaining
            )
            if msg_meta.get("cid") != cid:
                continue  # stale chain
            if msg_meta.get("chain_error"):
                raise _ChainError(
                    msg_meta["chain_error"],
                    permanent=bool(msg_meta.get("permanent")),
                )
            if int(msg_meta.get("i", -1)) != i:
                raise _ChainError(
                    f"chain step mismatch: got {msg_meta.get('i')}, "
                    f"expected {i}"
                )
            return np.asarray(msg_tensors[0]).reshape(-1)

    async def _run_chain_step(
        self, session: _Session, meta: dict, tensors: list
    ) -> None:
        """One pushed hop of a chained decode_n on a MIDDLE or TAIL span:
        run the span step; middles push hidden onward, the tail applies
        norm+head+select and pushes the ids back to the coordinator. All
        failures travel to the coordinator as chain_error pushes — never
        onto this span's own client stream (the client is not reading it
        mid-decode_n)."""

        chain = meta["chain"]
        origin = chain["origin"]
        deadline = self._local_deadline(meta)
        try:
            hidden = np.asarray(tensors[0])

            def _dispatch():
                if not self.manager.epoch_valid(session.handle):
                    raise SessionKVLost(
                        "server KV arena was rebuilt; session cache lost "
                        "— replay"
                    )
                session.last_step_at = clock.monotonic()
                return self.executor.decode(
                    session.handle, hidden, commit=True,
                    layers=session.layers, fetch=False,
                    adapter=session.adapter,
                )

            route = meta.get("route") or []
            if not route:
                # tail role: eligibility must be checked before committing
                # anything downstream of a doomed chain is pointless — but
                # the coordinator already committed this round regardless,
                # so dirty replay handles either ordering; check first to
                # fail the cheapest way
                err = await self._chain_tail_ineligible(meta)
                if err is not None:
                    raise _ChainError(err, permanent=True)
            try:
                out_dev = await self.compute.submit(
                    PRIORITY_INFERENCE, _dispatch, deadline=deadline
                )
            except DeadlineExpired:
                # the coordinator's chain deadline already fired; it has
                # answered its client, so a chain_error would land on a
                # stale cid anyway — count the drop and stop quietly
                self._note_deadline_expired(meta, "in chain hop queue")
                return
            session.n_steps += 1
            session.sum_tokens += int(hidden.shape[0])
            if route:
                out = await asyncio.to_thread(self.executor.fetch, out_dev)
                remaining = None
                if deadline is not None:
                    remaining = deadline - clock.monotonic()
                    if remaining <= 0:
                        self._note_deadline_expired(
                            meta, "before chain forward"
                        )
                        return
                await self._push_hop(
                    route, chain, meta.get("step"), meta.get("head_dtype"),
                    out, deadline_s=remaining,
                )
            else:
                nxt = await self.compute.submit(
                    PRIORITY_INFERENCE, self._select_head, out_dev
                )
                conn = await self.peers.get(origin["host"], origin["port"])
                async with self.peers.limiter(
                    origin["host"], origin["port"]
                ).slot():
                    await conn.push(
                        "rpc_push",
                        {
                            "session_id": origin["session_id"],
                            "chain_ids": True,
                            "cid": chain.get("cid"),
                            "i": chain.get("i"),
                        },
                        [nxt.astype(np.int32)],
                    )
        except Exception as e:
            logger.warning("chain step failed: %s", e)
            try:
                conn = await self.peers.get(origin["host"], origin["port"])
                await conn.push(
                    "rpc_push",
                    {
                        "session_id": origin["session_id"],
                        "chain_error": f"{type(e).__name__}: {e}",
                        "permanent": bool(getattr(e, "permanent", False)),
                        "cid": chain.get("cid"),
                    },
                    [],
                )
            except Exception:
                pass  # coordinator's timeout covers a dead push path

    async def _chain_tail_ineligible(self, meta: dict) -> str | None:
        """Why this span cannot play the TAIL role (apply norm+head) of a
        chained decode_n; None when it can."""
        if self.end_block != self.spec.num_hidden_layers:
            return (
                f"span ends at block {self.end_block}, not the model's "
                f"last block {self.spec.num_hidden_layers}"
            )
        await self._ensure_client_params()
        if self._client_params is None:
            return "tail has no norm/lm_head params"
        want_dt = meta.get("head_dtype")
        have_dt = str(self._client_params["lm_head"].dtype)
        if want_dt is not None and want_dt != have_dt:
            return (
                f"head dtype mismatch: client {want_dt} vs tail {have_dt}"
            )
        return None

    def _embed_ids(self, ids: np.ndarray) -> np.ndarray:
        """ids [B] -> hidden [B, 1, D] fp32, numerically identical to the
        client's embed (client/model.py embed: same impl, same params
        loaded the same way, fp32 host result)."""
        from bloombee_tpu.models.head import embed_impl

        if not hasattr(self, "_embed_jit"):
            import functools

            import jax

            self._embed_jit = functools.partial(
                jax.jit,
                static_argnames=(
                    "embedding_multiplier", "has_embed_norm", "eps"
                ),
            )(embed_impl)
        h = self._embed_jit(
            self._client_params,
            jnp.asarray(np.asarray(ids, np.int64)[:, None]),
            self.spec.embedding_multiplier,
            "embed_norm" in self._client_params,
            self.spec.rms_norm_eps,
        )
        return np.asarray(h, dtype=np.float32)

    def _select_head(self, out_dev) -> np.ndarray:
        """Span output [B, 1, D] -> greedy next ids [B], via the same
        norm+head math and the same wire-dtype->fp32 cast as the client's
        per-step path (fetch as transfer dtype, cast fp32, norm+head,
        first-index argmax) so chained decode stays token-exact."""
        from bloombee_tpu.models.head import norm_head_impl

        if not hasattr(self, "_head_jit"):
            import functools

            import jax

            self._head_jit = functools.partial(
                jax.jit,
                static_argnames=("eps", "soft_cap", "norm_type"),
            )(norm_head_impl)
        out = np.asarray(out_dev).astype(self.executor.transfer_dtype)
        logits = self._head_jit(
            self._client_params,
            jnp.asarray(out[:, -1].astype(np.float32)),
            self.spec.rms_norm_eps,
            self.spec.logits_soft_cap,
            self.spec.norm_type,
        )
        return np.argmax(np.asarray(logits), axis=-1).astype(np.int64)

    def _decode_n_ineligible(self, session: _Session | None = None):
        """The session-independent (and, given a session, session-specific)
        reasons this server cannot run the FUSED decode_n scan (the
        host-driven stepped loop has weaker requirements — see
        _run_decode_n). Returns None when eligible, else a human-readable
        reason (surfaced in rpc_info/health as decode_n_decline)."""
        if session is not None and session.layers is not None:
            return "session routes a sub-span, not the whole model"
        # the loop applies the LM head after THIS span, so the span must
        # be the whole model, not a prefix
        if not (
            self.start_block == 0
            and self.end_block == self.spec.num_hidden_layers
        ):
            return (
                f"span [{self.start_block},{self.end_block}) is not the "
                f"whole model"
            )
        if self.spec.heterogeneous:
            return "heterogeneous head_dim span"
        if self.executor.host_layers:
            return "span has weight-offloaded layers"
        if self.executor.mesh is not None:
            return "span is TP-sharded"
        if self.manager.quant is not None:
            return "quantized KV arena"
        # sparse decode recomputes k per step on the per-step path; a
        # frozen k inside the scan would break token-exactness
        if self.executor.attn_sparsity < 1.0:
            return "sparse decode attention"
        if self._client_params_unavailable or (
            self._client_params is None and self.model_dir is None
        ):
            return "server has no embed/norm/lm_head params"
        return None

    async def _ensure_client_params(self) -> None:
        if (
            self._client_params is not None
            or self._client_params_unavailable
        ):
            return
        if self.model_dir is None:
            self._client_params_unavailable = True
            return
        if self._client_params_lock is None:
            self._client_params_lock = lockwatch.async_lock(
                "server.client_params"
            )
        async with self._client_params_lock:
            if (
                self._client_params is None
                and not self._client_params_unavailable
            ):
                # multi-GB safetensors read: off the event loop
                await asyncio.to_thread(self._load_client_params)

    def _load_client_params(self) -> None:
        try:
            from bloombee_tpu.models.checkpoint import load_client_params

            # checkpoint-native dtype: the client loads the same tensors the
            # same way, keeping the server loop's logits identical to the
            # client's per-step head on the same backend
            self._client_params = load_client_params(self.model_dir)
        except Exception as e:
            logger.warning("decode_n unavailable (client params): %s", e)
            self._client_params_unavailable = True

    # ------------------------------------------- stall-free chunked prefill
    def _chunk_budget(self) -> int:
        """Per-prefill chunk token budget: the server ctor value wins,
        else BBTPU_PREFILL_CHUNK; 0 disables (monolithic prefill)."""
        if self.prefill_chunk is not None:
            return int(self.prefill_chunk)
        return int(env.get("BBTPU_PREFILL_CHUNK"))

    def _chunk_spans(
        self, hidden, commit, tree_mask, commit_lens
    ) -> list[tuple[int, int]] | None:
        """[start, end) chunk spans for this step, or None when the step
        must stay one monolithic compute task. Only plain committing
        prefills chunk: tree steps aren't prefills, speculative
        (commit=False) and ragged-replay steps own bespoke table side
        effects, and sp-mesh servers hand long prompts to ring attention
        (which needs the whole prompt in one call). A suffix prefill after
        a prefix-cache adoption chunks too — the adoption settles before
        the first chunk."""
        budget = self._chunk_budget()
        if (
            budget <= 0
            or hidden.shape[1] <= 1
            or tree_mask is not None
            or not commit
            or commit_lens is not None
            or self.executor.sp_mesh is not None
        ):
            return None
        spans = plan_prefill_chunks(
            hidden.shape[1], budget, cap=self.executor.max_chunk_tokens
        )
        return spans if len(spans) > 1 else None

    async def _run_chunked_prefill(
        self, session: _Session, handle, hidden, spans, deadline,
        prefix_skip=None,
    ):
        """Drive one prefill as a stream of resumable chunk tasks. Each
        chunk is its own compute-queue submission at an AGING chunk
        priority (fresh streams yield to queued decode steps; an old
        stream reaches decode priority, so it cannot starve), with the
        client deadline re-checked both between chunks (here) and at each
        chunk's queue pop (the submit's deadline=).

        Chunks write their KV speculatively; the LAST chunk's compute-
        thread slot commits the whole prompt (same pattern as the batched
        decode path), so any abort — deadline expiry, a failed chunk, a
        lost arena — rolls back and frees every partial page. Returns
        (per-chunk lazy outputs, total dispatch ms); `executor.fetch`
        concatenates the chunk list off-queue."""

        stream_t0 = clock.monotonic()
        outs: list = []
        total_ms = 0.0
        last = len(spans) - 1
        self._chunking_sessions += 1
        try:
            for idx, (s, e) in enumerate(spans):
                if self._deadline_passed(deadline):
                    raise DeadlineExpired(
                        "client deadline expired between prefill chunks"
                    )
                if self.mixed_batch:
                    # batchable chunk: the worker may fuse this chunk with
                    # queued decode steps — and, with --spec-batch also
                    # on, tree-verify rows — into one ragged dispatch (and
                    # a popped decode may likewise absorb this chunk)
                    out, dt_ms = await self.compute.submit_group(
                        aged_chunk_priority(stream_t0),
                        ("chunkm", session.layers, session.adapter,
                         str(hidden.dtype), e - s),
                        _ChunkMember(
                            session, handle, hidden[:, s:e],
                            idx == 0, idx == last, prefix_skip,
                        ),
                        self._compute_ragged_group,
                        deadline=deadline,
                        task_class="prefill",
                    )
                else:
                    out, dt_ms = await self.compute.submit(
                        aged_chunk_priority(stream_t0),
                        self._compute_prefill_chunk,
                        session,
                        handle,
                        hidden[:, s:e],
                        idx == 0,
                        idx == last,
                        prefix_skip,
                        deadline=deadline,
                        task_class="prefill",
                    )
                outs.append(out)
                total_ms += dt_ms
                self.prefill_chunks += 1
                self.prefill_chunk_tokens += int(hidden.shape[0]) * (e - s)
        except BaseException:
            # free the partial prefill's speculative pages — a session
            # holding pages for a prompt nobody will finish is a leak
            # until close; deadline-driven aborts especially must release
            # capacity NOW (that is the point of aborting)
            await self._abort_chunked_prefill(handle)
            raise
        finally:
            self._chunking_sessions -= 1
        return outs, total_ms

    async def _abort_chunked_prefill(self, handle) -> None:
        """Roll the handle back to its committed state, freeing the
        aborted prefill's speculative pages. Runs on the compute thread —
        the only thread that mutates the paged table — and is epoch-
        guarded: an arena rebuild already invalidated (and freed) the
        session's table state."""
        try:
            await self.compute.submit(
                PRIORITY_INFERENCE, self._rollback_if_valid, handle
            )
        except Exception:
            logger.warning(
                "chunked-prefill rollback failed; pages free at session "
                "close instead", exc_info=True,
            )

    def _rollback_if_valid(self, handle) -> None:
        if self.manager.epoch_valid(handle):
            self.manager.rollback(handle)

    def _compute_prefill_chunk(
        self, session: _Session, handle, hidden, first, last,
        prefix_skip=None,
    ):
        """Runs on the compute thread: one chunk of a chunked prefill.
        Same contract as _compute_step (dispatch only; fetch happens
        off-queue) with the chunk-stream twists: the FIRST chunk settles
        a pending prefix-cache adoption, every chunk writes speculatively,
        and the LAST chunk commits the whole prompt."""

        if not self.manager.epoch_valid(handle):
            raise SessionKVLost(
                "server KV arena was rebuilt; session cache lost — replay"
            )
        session.last_step_at = clock.monotonic()
        t0 = clock.perf_counter()
        if first and self.manager.has_adopted(handle):
            # settle the probe adoption before the suffix's first chunk
            # (same semantics as _compute_step's settle)
            self.manager.ensure_resident(handle)
            self.manager.trim_adopted(handle, int(prefix_skip or 0))
        session.adoption_settled = True
        # recovery owner: _run_chunked_prefill's except BaseException ->
        # _abort_chunked_prefill (epoch-guarded rollback); this helper
        # runs only inside that stream driver
        out = self.executor.prefill_chunk(  # bbtpu: noqa[BB001]
            handle, hidden, commit=False, layers=session.layers,
            fetch=False, adapter=session.adapter,
        )
        if last:
            self.manager.commit(handle)
        self.step_dispatches += 1
        self.step_tokens += int(hidden.shape[0]) * int(hidden.shape[1])
        dt_ms = (clock.perf_counter() - t0) * 1000.0
        if env.log_channel_enabled("timing"):
            logger.info(
                "[timing] session=%s prefill chunk tokens=%d%s "
                "dispatch_ms=%.2f",
                session.id, hidden.shape[1],
                " (final)" if last else "", dt_ms,
            )
        return out, dt_ms

    def _compute_step(
        self, session: _Session, handle, hidden, commit, tree_mask,
        depths=None, commit_lens=None, prefix_skip=None,
    ):
        """Runs on the compute thread: plan packing + async device dispatch
        only (the d2h fetch happens off-queue in _run_step). The dispatch
        time is the serialized cost per step — the unit that bounds server
        throughput (reference [TIMING_TABLE] decomposition,
        handler.py:1276-1605)."""

        if not self.manager.epoch_valid(handle):
            # the arena was rebuilt after a kernel failure and this
            # session's KV was device-resident (not parked): its table
            # state describes KV that no longer exists — fail loudly with
            # the typed error so the client replays without banning us
            # (a silent step would compute on a zeroed context)
            raise SessionKVLost(
                "server KV arena was rebuilt; session cache lost — replay"
            )
        session.last_step_at = clock.monotonic()
        t0 = clock.perf_counter()
        if self.manager.has_adopted(handle):
            # settle an outstanding probe adoption: unpark first so the
            # trim acts on live lengths, then shrink each row's adopted
            # prefix to the chain-wide skip the client actually uses. A
            # step that never declares prefix_skip drops the adoption
            # entirely (skip 0) — the safe interpretation of a client that
            # changed its mind (or a stale retry).
            self.manager.ensure_resident(handle)
            self.manager.trim_adopted(
                handle, int(prefix_skip or 0)
            )
        session.adoption_settled = True
        if hidden.shape[1] > 1 and tree_mask is None:
            out = self.executor.prefill(
                handle, hidden, commit=commit, layers=session.layers,
                fetch=False, adapter=session.adapter,
            )
        else:
            if hidden.shape[1] == 1 and self._chunking_sessions:
                # a decode step ran while some session's chunked prefill
                # was mid-stream: the stall this scheduler removes
                self.decode_steps_interleaved += 1
            out = self.executor.decode(
                handle, hidden, commit=commit, tree_mask=tree_mask,
                layers=session.layers, depths=depths, fetch=False,
                adapter=session.adapter,
            )
        if commit_lens is not None:
            # ragged explicit-length commit only happens on an id-session
            # failover replay: account the replayed tokens so the chaos
            # tests can assert the replication bound from rpc_info
            self.manager.commit(handle, lengths=commit_lens)
            self.failover_replayed_tokens += int(
                hidden.shape[0] * hidden.shape[1]
            )
        self.step_dispatches += 1
        self.step_tokens += int(hidden.shape[0]) * int(hidden.shape[1])
        dt_ms = (clock.perf_counter() - t0) * 1000.0
        if env.log_channel_enabled("timing"):
            logger.info(
                "[timing] session=%s tokens=%d dispatch_ms=%.2f",
                session.id, hidden.shape[1], dt_ms,
            )
        return out, dt_ms

    def _batchable(
        self, commit, hidden, tree_mask, depths, commit_lens,
        prefix_skip=None,
    ) -> bool:
        """Whether this step may share a merged dispatch: plain committing
        single-token decode only. Tree-verify steps, prefills, ragged
        replays and speculative (commit=False) steps keep their own
        compute task — their table side effects are per-session. A step
        declaring prefix_skip is a suffix PREFILL even at one token (a
        warm prefix hit can shrink the uncached tail that far) and must
        settle its adoption on the solo path. A draining server also
        stops coalescing: its sessions are winding down and the simple
        per-step path keeps the drain predictable."""
        return (
            self.max_batch > 1
            and hidden.shape[1] == 1
            and tree_mask is None
            and depths is None
            and commit_lens is None
            and prefix_skip is None
            and commit
            and not self._draining
        )

    def _compute_step_group(self, members: list[_BatchMember]) -> list:
        """Runs on the compute thread: execute a group of compatible
        single-token decode steps as ONE merged span dispatch. Returns one
        outcome per member — (lazy out rows, dispatch_ms) or an Exception
        instance, which the queue raises only at that member's caller.

        Members whose KV can't safely join the merged dispatch (stale
        epoch, host-parked) fall out to the solo path so their failure
        modes stay their own; if the merged dispatch itself fails, its
        speculative writes roll back and the group replays row-by-row, so
        one member's fault never sinks its co-batched peers."""
        results: list = [None] * len(members)
        ready: list[int] = []
        for i, m in enumerate(members):
            if not self.manager.epoch_valid(m.handle):
                results[i] = SessionKVLost(
                    "server KV arena was rebuilt; session cache lost — "
                    "replay"
                )
            elif (self.manager.has_parked(m.handle)
                  or (not m.session.adoption_settled
                      and self.manager.has_adopted(m.handle))):
                # unparking inside a merged dispatch could OutOfPages the
                # whole batch; alone, only this member wears the failure.
                # An UNSETTLED prefix adoption likewise needs the solo
                # path (_compute_step trims it to the declared skip before
                # computing) — but only until its first step settles it:
                # a settled adopted session batches like any other instead
                # of soloing for the rest of its life
                results[i] = self._solo_member_step(m)
            else:
                ready.append(i)
        if len(ready) == 1:
            results[ready[0]] = self._solo_member_step(members[ready[0]])
        elif ready:
            group = [members[i] for i in ready]
            try:
                outs = self._dispatch_batched(group)
            except Exception as e:
                logger.warning(
                    "batched decode of %d sessions failed (%r); "
                    "replaying row-by-row", len(group), e,
                )
                outs = [self._solo_member_step(m) for m in group]
            for i, out in zip(ready, outs):
                results[i] = out
        return results

    def _solo_member_step(self, m: _BatchMember):
        self.batch_solo_steps += 1
        ledger.recovery("server.rollback_solo_replay")
        try:
            return self._compute_step(
                m.session, m.handle, m.hidden, True, None
            )
        except Exception as e:
            return e

    def _dispatch_batched(self, group: list[_BatchMember]) -> list:
        """One row-stacked span dispatch for >= 2 sessions' decode steps.
        KV writes go in speculatively and commit only after the dispatch
        succeeds, so a failure rolls the whole group's tables back to the
        pre-step state and the row-by-row replay appends no ghost tokens."""

        t0 = clock.perf_counter()
        now = clock.monotonic()
        for m in group:
            m.session.last_step_at = now
        handles = [m.handle for m in group]
        try:
            out, combined = self.executor.decode_group(
                handles,
                [m.hidden for m in group],
                layers=group[0].session.layers,
                adapter=group[0].session.adapter,
            )
        except Exception:
            self.manager.rollback(self.manager.combine_handles(handles))
            raise
        self.manager.commit(combined)
        dt_ms = (clock.perf_counter() - t0) * 1000.0
        self.batch_dispatches += 1
        self.batched_steps += len(group)
        self.step_dispatches += 1
        self.step_tokens += sum(m.handle.batch_size for m in group)
        if self._chunking_sessions:
            self.decode_steps_interleaved += len(group)
        if env.log_channel_enabled("timing"):
            logger.info(
                "[timing] batched decode: %d sessions, %d rows, "
                "dispatch_ms=%.2f",
                len(group), sum(m.handle.batch_size for m in group), dt_ms,
            )
        outs = []
        row = 0
        for m in group:
            b = m.handle.batch_size
            outs.append((out[row:row + b], dt_ms))
            row += b
        return outs

    # ----------------------------------------- batched tree verification
    def _tree_batchable(
        self, commit, tree_mask, depths, commit_lens, meta
    ) -> bool:
        """Whether this tree-verify step may share a batched ragged
        dispatch (--spec-batch): a plain speculative (commit=False) tree
        step with depth positions. Pruned relay steps keep the solo path
        (their keep-set reply is computed per session against the solo
        step's layout), as do failover replays (commit_lens) and
        prefix-skip settles; a draining server stops coalescing."""
        return (
            self.spec_batch
            and self.max_batch > 1
            and tree_mask is not None
            and depths is not None
            and not commit
            and commit_lens is None
            and meta.get("prune") is None
            and meta.get("prefix_skip") is None
            and not self._draining
        )

    def _compute_tree_group(self, members: list[_TreeMember]) -> list:
        """PR-10 surface: thin delegation onto the unified ragged runner
        (a tree-only group packs and rolls back exactly as the dedicated
        tree stack used to)."""
        return self._compute_ragged_group(members)

    def _solo_tree_step(self, m: _TreeMember):
        self.batch_solo_steps += 1
        try:
            return self._compute_step(
                m.session, m.handle, m.hidden, False, m.tree_mask,
                m.depths,
            )
        except Exception as e:
            return e

    # ----------------------------------------- universal ragged dispatch
    def _batch_group_hint(self, members: list | None = None) -> int:
        """Upper bound on how many members a ComputeQueue gather window
        could still collect: a session submits at most one step (or
        prefill chunk) at a time, so once every open session is in the
        group the window is pure dead time — a solo session never waits
        it out at all.

        KIND-AWARE when only one of the batching flags is on: a tree-only
        gather can admit nothing but tree rows, so it is bounded by the
        sessions currently speculating (without this, tree groups slept
        the full window whenever any non-speculating session was open —
        the phase-lock caveat PR 10 root-caused); symmetrically, a causal
        gather can't admit a speculating session's tree row. With BOTH
        flags on every kind fuses, so every open session counts."""
        total = len(self._sessions)
        if not members or (self.mixed_batch and self.spec_batch):
            return total
        speculating = sum(
            1 for s in self._sessions.values() if s.speculating
        )
        if all(m.key[0] == "tree" for m in members):
            return speculating
        if self.spec_batch:
            return total - speculating
        return total

    def _ragged_compat(self, members: list, cand) -> bool:
        """ONE kind-aware ComputeQueue group-membership predicate for the
        universal ragged dispatch. Mixable kinds follow the flags: decode
        steps ("decode1") and prefill chunks ("chunkm") with
        --mixed-batch (PR 8), tree-verify rows ("tree") with --spec-batch
        (PR 10), and all three fuse cross-kind when both are on. Members
        must agree on layers/adapter/dtype, a group holds at most ONE
        chunk (the ragged step models N row-groups + one chunk row-group)
        and at most max_batch non-chunk members (the chunk rides the +1
        group slot, never a batch seat). Any non-mixable kind falls back
        to exact-key coalescing."""
        mixable = set()
        if self.mixed_batch:
            mixable |= {"decode1", "chunkm"}
        if self.spec_batch:
            mixable.add("tree")
        keys = [m.key for m in members]
        if cand.key[0] not in mixable or keys[0][0] not in mixable:
            return cand.key == keys[0]
        if any(k[1:4] != cand.key[1:4] for k in keys):
            return False
        kinds = [k[0] for k in keys]
        if cand.key[0] == "chunkm":
            return "chunkm" not in kinds
        return sum(1 for k in kinds if k != "chunkm") < self.max_batch

    def _compute_mixed_group(self, members: list) -> list:
        """PR-8 surface: thin delegation onto the unified ragged runner
        (decode+chunk groups pack, commit and roll back exactly as the
        dedicated mixed stack used to)."""
        return self._compute_ragged_group(members)

    def _compute_ragged_group(self, members: list) -> list:
        """Runs on the compute thread: ONE group that may hold decode
        steps, tree-verify steps AND one prefill chunk, in any mix the
        compat predicate admitted. Returns one outcome per member — (lazy
        out, dispatch_ms) or an Exception instance, which the queue
        raises only at that member's caller.

        Same member hygiene as _compute_step_group: stale-epoch members
        fail typed; parked / adoption-unsettled members fall out to their
        kind's solo path (their table side effects stay their own).
        Chunk-free all-decode groups take the classic merged-decode path
        (identical outcomes to _compute_step_group); everything else runs
        as ONE ragged span dispatch via executor.ragged_group, with
        per-kind solo replay if the fused dispatch fails so one member's
        fault never sinks its peers."""
        results: list = [None] * len(members)
        decode_idx: list[int] = []
        tree_idx: list[int] = []
        chunk_idx: list[int] = []
        for i, m in enumerate(members):
            if not self.manager.epoch_valid(m.handle):
                results[i] = SessionKVLost(
                    "server KV arena was rebuilt; session cache lost — "
                    "replay"
                )
            elif isinstance(m, _ChunkMember):
                if (self.manager.has_parked(m.handle)
                        or (m.first and self.manager.has_adopted(m.handle))):
                    # unpark / adoption settle mutate the table mid-group;
                    # the solo chunk path owns those side effects
                    results[i] = self._solo_chunk_step(m)
                else:
                    chunk_idx.append(i)
            elif (self.manager.has_parked(m.handle)
                  or (not m.session.adoption_settled
                      and self.manager.has_adopted(m.handle))):
                # same solo carve-outs as _compute_step_group
                results[i] = (
                    self._solo_tree_step(m) if isinstance(m, _TreeMember)
                    else self._solo_member_step(m)
                )
            elif isinstance(m, _TreeMember):
                tree_idx.append(i)
            else:
                decode_idx.append(i)
        if not chunk_idx and not tree_idx:
            # no chunk, no trees: exact _compute_step_group semantics
            if len(decode_idx) == 1:
                results[decode_idx[0]] = self._solo_member_step(
                    members[decode_idx[0]]
                )
            elif decode_idx:
                group = [members[i] for i in decode_idx]
                try:
                    outs = self._dispatch_batched(group)
                except Exception as e:
                    logger.warning(
                        "batched decode of %d sessions failed (%r); "
                        "replaying row-by-row", len(group), e,
                    )
                    outs = [self._solo_member_step(m) for m in group]
                for i, out in zip(decode_idx, outs):
                    results[i] = out
            return results
        if not decode_idx and not tree_idx:
            results[chunk_idx[0]] = self._solo_chunk_step(members[chunk_idx[0]])
            return results
        if len(tree_idx) == 1 and not decode_idx and not chunk_idx:
            results[tree_idx[0]] = self._solo_tree_step(members[tree_idx[0]])
            return results
        # member-major row order: decodes, then trees, then the chunk
        # LAST (its multi-token row-group caps the ragged packing)
        order = decode_idx + tree_idx + chunk_idx
        group = [members[i] for i in order]
        try:
            outs = self._dispatch_ragged(group)
        except Exception as e:
            logger.warning(
                "ragged dispatch of %d decodes + %d trees + %d chunks "
                "failed (%r); replaying solo",
                len(decode_idx), len(tree_idx), len(chunk_idx), e,
            )
            outs = []
            for m in group:
                if isinstance(m, _ChunkMember):
                    outs.append(self._solo_chunk_step(m))
                elif isinstance(m, _TreeMember):
                    outs.append(self._solo_tree_step(m))
                else:
                    outs.append(self._solo_member_step(m))
        for i, out in zip(order, outs):
            results[i] = out
        return results

    def _solo_chunk_step(self, m: _ChunkMember):
        try:
            return self._compute_prefill_chunk(
                m.session, m.handle, m.hidden, m.first, m.last,
                m.prefix_skip,
            )
        except Exception as e:
            return e

    def _dispatch_ragged(self, group: list) -> list:
        """ONE universal ragged span dispatch for any admitted mix of
        decode steps, tree-verify steps and at most one prefill chunk
        (the chunk, if present, is group[-1]). Every member's KV writes
        go in speculatively and commit/rollback stays PER KIND, exactly
        as the three dedicated stacks did:

        - decode members commit after the dispatch succeeds and roll
          back to their committed state on failure;
        - the chunk commits only on its stream's LAST chunk and is
          TRUNCATED to its pre-dispatch length on failure (a plain
          rollback would also discard the stream's earlier, still-wanted
          speculative chunks);
        - tree members never commit here — on failure each truncates
          back to its pre-dispatch committed length and replays solo; on
          success the surviving slots settle when the session's next
          accept rides in (accept_speculative, unchanged)."""

        t0 = clock.perf_counter()
        now = clock.monotonic()
        for m in group:
            m.session.last_step_at = now
        chunk = group[-1] if isinstance(group[-1], _ChunkMember) else None
        decodes = [m for m in group if isinstance(m, _BatchMember)]
        trees = [m for m in group if isinstance(m, _TreeMember)]
        # pre-dispatch speculative lengths: the truncate targets on
        # failure for the chunk and for every tree member
        chunk_snap = (
            [int(x) for x in self.manager.context_lens(chunk.handle)]
            if chunk is not None else None
        )
        tree_snaps = [
            [int(x) for x in self.manager.context_lens(m.handle)]
            for m in trees
        ]
        try:
            out, _combined = self.executor.ragged_group(
                [m.handle for m in group],
                [m.hidden for m in group],
                tree_masks=[
                    m.tree_mask if isinstance(m, _TreeMember) else None
                    for m in group
                ],
                depths_list=[
                    m.depths if isinstance(m, _TreeMember) else None
                    for m in group
                ],
                layers=group[0].session.layers,
                adapter=group[0].session.adapter,
            )
        except Exception:
            if chunk is not None and self.manager.epoch_valid(chunk.handle):
                self.manager.truncate_speculative(chunk.handle, chunk_snap)
            for m, snap in zip(trees, tree_snaps):
                if self.manager.epoch_valid(m.handle):
                    self.manager.truncate_speculative(m.handle, snap)
            for m in decodes:
                if self.manager.epoch_valid(m.handle):
                    self.manager.rollback(m.handle)
            raise
        for m in decodes:
            self.manager.commit(m.handle)
        if chunk is not None and chunk.last:
            self.manager.commit(chunk.handle)
        dt_ms = (clock.perf_counter() - t0) * 1000.0
        ntok = sum(
            m.handle.batch_size * int(m.hidden.shape[1]) for m in group
        )
        self.ragged_group_dispatches += 1
        kinds = (
            (1 if decodes else 0) + (1 if trees else 0)
            + (1 if chunk is not None else 0)
        )
        if kinds > 1:
            self.ragged_cross_kind_dispatches += 1
        if chunk is not None:
            self.mixed_dispatches += 1
            self.mixed_tokens += ntok
        if trees:
            self.tree_group_dispatches += 1
            self.tree_group_members += len(trees)
        self.step_dispatches += 1
        self.step_tokens += ntok
        if chunk is not None:
            # the decodes/trees literally ran inside a mid-stream
            # prefill's dispatch
            self.decode_steps_interleaved += len(group) - 1
        elif self._chunking_sessions:
            self.decode_steps_interleaved += len(group)
        if env.log_channel_enabled("timing"):
            logger.info(
                "[timing] ragged dispatch: %d decodes + %d trees + "
                "%d-token chunk, %d rows, dispatch_ms=%.2f",
                len(decodes), len(trees),
                int(chunk.hidden.shape[1]) if chunk is not None else 0,
                sum(
                    m.handle.batch_size * int(m.hidden.shape[1])
                    for m in group
                ), dt_ms,
            )
        # slice the member-major token-packed [R, D] result back out:
        # decode members get [b, 1, D], trees and the chunk [b, t, D]
        outs = []
        off = 0
        for m in group:
            b = m.handle.batch_size
            t = int(m.hidden.shape[1])
            outs.append(
                (out[off:off + b * t].reshape(b, t, -1), dt_ms)
            )
            off += b * t
        return outs

    def _reclaim_idle(self, need_pages: int, exclude_seq_ids: set) -> int:
        """Park idle sessions' KV (LRU by last step) until `need_pages` are
        freed. Runs on the compute thread — the only thread that mutates
        the paged table — so no step can race the eviction."""

        now = clock.monotonic()
        victims = sorted(
            (
                s for s in list(self._sessions.values())
                if now - s.last_step_at >= self.idle_park_s
                and not (set(s.handle.seq_ids) & exclude_seq_ids)
            ),
            key=lambda s: s.last_step_at,
        )
        freed = 0
        for sess in victims:
            if freed >= need_pages:
                break
            for sid in sess.handle.seq_ids:
                try:
                    if (
                        self.manager.table.has_seq(sid)
                        and sid not in self.manager._parked
                        and self.manager.table.seq(sid).l_seq > 0
                    ):
                        before = self.manager.table.free_pages
                        self.manager.park_sequence(sid)
                        freed += self.manager.table.free_pages - before
                except KeyError:
                    continue  # session tore down between snapshot and park
            logger.info(
                "parked idle session %s (freed %d pages so far)",
                sess.id, freed,
            )
        return freed

    def _dump_activations(
        self, dump_dir: str, session: _Session, meta: dict,
        hidden: np.ndarray, out: np.ndarray
    ) -> None:
        """Capture real per-step hidden states for compression research
        (reference utils/real_activation_dumper.py, hooked at
        backend.inference_step:500)."""
        import os

        n = getattr(self, "_dump_count", 0)
        if n >= env.get("BBTPU_DUMP_LIMIT"):
            return
        self._dump_count = n + 1
        os.makedirs(dump_dir, exist_ok=True)
        rows = meta.get("rows")
        suffix = f"_rows{rows[0]}-{rows[1]}" if rows else ""
        path = os.path.join(
            dump_dir,
            f"{self.server_id}_{session.id}_step{meta.get('step')}"
            f"{suffix}.npz",
        )
        np.savez(
            path,
            hidden_in=np.asarray(hidden, dtype=np.float32),
            hidden_out=np.asarray(out, dtype=np.float32),
            start_block=self.start_block,
            end_block=self.end_block,
        )

    async def _train_pruner_head(self, session: _Session, accept: list):
        """Online MidLMHead training (reference lm_head_trainer): each
        accepted (parent -> child) edge supplies (mid_hidden[parent],
        token[child]) — the full model chose token[child] there, so the
        head learns to predict it from mid-network state. Device work runs
        on the compute queue at training priority; file I/O off-loop."""
        mgr = self._pruner_manager
        if mgr is None or getattr(mgr, "trainer", None) is None:
            session.last_tree = None
            return
        hidden, tokens, parents = session.last_tree
        session.last_tree = None
        feats, targets = [], []
        for i, acc in enumerate(accept):
            path = [int(a) for a in np.asarray(acc).ravel()]
            for parent, child in zip(path, path[1:]):
                feats.append(hidden[i, parent])
                targets.append(int(tokens[i, child]))
        if not feats:
            return
        try:
            loss = await self.compute.submit(
                PRIORITY_TRAINING, mgr.trainer.train_step,
                np.stack(feats), np.asarray(targets, dtype=np.int64),
            )
        except Exception as e:
            logger.warning("pruner-head train step failed: %s", e)
            return
        if getattr(mgr, "neural_trainer", None) is not None:
            await self._train_neural_pruner(
                mgr, hidden, tokens, parents, accept
            )
        if env.log_channel_enabled("spec"):
            logger.info(
                "[pruner-train] step=%d pairs=%d loss=%.3f",
                mgr.trainer.steps, len(targets), loss,
            )
        ckpt = env.get("BBTPU_PRUNER_CKPT")
        if ckpt and mgr.trainer.steps % 50 == 0:
            try:
                await asyncio.to_thread(mgr.trainer.save, ckpt)
            except Exception as e:
                logger.warning("pruner checkpoint save failed: %s", e)

    async def _train_neural_pruner(self, mgr, hidden, tokens, parents,
                                   accept):
        """Online BCE training of the learned keep/prune scorer (reference
        adaptive_neural_pruner collect_training_data): recompute each
        row's probability features under the CURRENT head, label
        accepted-path nodes 1 and drafted-but-rejected nodes 0."""
        from bloombee_tpu.spec.pruner import node_features
        from bloombee_tpu.spec.tree import DraftTree

        bsz, t = tokens.shape

        def _head_probs():
            # ONE small matmul: rides the compute queue at training
            # priority like every other device forward (the queue's
            # documented contract is that all device work funnels through
            # its single thread — advisor, round 4), while the O(B*T)
            # numpy feature loop below stays on a plain worker thread.
            return mgr._head.probs(
                hidden.reshape(bsz * t, -1).astype(np.float32)
            ).reshape(bsz, t, -1)

        def _build_features(all_probs):
            feat_rows, label_rows = [], []
            for i, acc in enumerate(accept):
                tree = DraftTree(tokens=tokens[i], parents=parents)
                root = np.zeros(all_probs.shape[2], dtype=np.float64)
                root[int(tokens[i, 0])] = 1.0
                feat_rows.append(node_features(tree, all_probs[i], root))
                lbl = np.zeros((t,), dtype=np.float32)
                for node in np.asarray(acc).ravel():
                    if 0 <= int(node) < t:
                        lbl[int(node)] = 1.0
                label_rows.append(lbl)
            return np.concatenate(feat_rows), np.concatenate(label_rows)

        try:
            all_probs = await self.compute.submit(
                PRIORITY_TRAINING, _head_probs
            )
            feats, labels = await asyncio.to_thread(
                _build_features, all_probs
            )
            loss = await self.compute.submit(
                PRIORITY_TRAINING, mgr.neural_trainer.train_step,
                feats, labels,
            )
        except Exception as e:
            logger.warning("neural pruner train step failed: %s", e)
            return
        if env.log_channel_enabled("spec"):
            logger.info(
                "[pruner-net-train] step=%d loss=%.3f",
                mgr.neural_trainer.steps, loss,
            )
        ckpt = env.get("BBTPU_PRUNER_CKPT")
        if ckpt and mgr.neural_trainer.steps % 50 == 0:
            try:
                await asyncio.to_thread(
                    mgr.neural_trainer.save, f"{ckpt}.net"
                )
            except Exception as e:
                logger.warning("neural pruner checkpoint save failed: %s", e)

    def _prune_tree(self, out: np.ndarray, prune: dict):
        """Per-row keep indices from the MidLMHead over this span's output
        hidden; None if no pruner weight is available (degrade to full)."""
        mgr = self._ensure_pruner(float(prune.get("threshold", 0.05)))
        if mgr is None:
            return None
        from bloombee_tpu.spec.tree import DraftTree

        tokens = np.asarray(prune["tokens"], dtype=np.int64)  # [B, T]
        parents = np.asarray(prune["parents"], dtype=np.int32)
        max_keep = int(prune.get("max_keep", tokens.shape[1]))
        mgr._pruner.max_keep = max_keep
        bsz, t = tokens.shape
        # one batched head call for every row's nodes (per-step hot path)
        all_probs = mgr._head.probs(
            np.asarray(out, dtype=np.float32).reshape(bsz * t, -1)
        ).reshape(bsz, t, -1)
        rows = []
        for i in range(bsz):
            tree = DraftTree(tokens=tokens[i], parents=parents)
            # node 0 is the certain token: its "root" distribution is a
            # one-hot so it always survives the threshold
            root = np.zeros(all_probs.shape[2], dtype=np.float64)
            root[int(tokens[i, 0])] = 1.0
            rows.append(mgr._pruner.keep_indices(tree, all_probs[i], root))
        return np.stack(rows)

    async def _ensure_pruner_loaded(self) -> None:
        if self._pruner_manager is not None or self._pruner_unavailable:
            return
        if self._pruner_lock is None:
            self._pruner_lock = lockwatch.async_lock("server.pruner")
        async with self._pruner_lock:
            if self._pruner_manager is None and not self._pruner_unavailable:
                await asyncio.to_thread(self._load_pruner)

    def _load_pruner(self) -> None:
        if self.model_dir is None:
            self._pruner_unavailable = True
            return
        try:
            import os

            from bloombee_tpu.spec.pruner import (
                MidHeadTrainer,
                NeuralPrunerTrainer,
                PrunerManager,
            )

            method = env.get("BBTPU_PRUNER_METHOD")
            mgr = PrunerManager(method=method)
            ckpt = env.get("BBTPU_PRUNER_CKPT")
            if method == "neural":
                # the learned scorer has its own sidecar checkpoint
                net_ckpt = f"{ckpt}.net" if ckpt else ""
                import os as _os

                if net_ckpt and _os.path.exists(
                    MidHeadTrainer.ckpt_path(net_ckpt)
                ):
                    try:
                        mgr.neural_trainer = NeuralPrunerTrainer.load(
                            net_ckpt
                        )
                        mgr._pruner = mgr.neural_trainer.pruner
                    except Exception as e:
                        logger.warning(
                            "neural pruner checkpoint unreadable (%s); "
                            "fresh init", e,
                        )
                        mgr.neural_trainer = NeuralPrunerTrainer(mgr._pruner)
                else:
                    mgr.neural_trainer = NeuralPrunerTrainer(mgr._pruner)
            else:
                mgr.neural_trainer = None
            trainer = None
            if ckpt and os.path.exists(MidHeadTrainer.ckpt_path(ckpt)):
                try:
                    # resume a previously trained head (reference
                    # adaptive_neural_pruner.load_model)
                    trainer = MidHeadTrainer.load(
                        ckpt, dtype=self.compute_dtype
                    )
                    mgr._head = trainer.head
                except Exception as e:
                    # a torn checkpoint must degrade to fresh init, never
                    # disable pruning outright
                    logger.warning(
                        "pruner checkpoint unreadable (%s); fresh init", e
                    )
                    trainer = None
            if trainer is None:
                from bloombee_tpu.models.checkpoint import load_client_params

                client = load_client_params(
                    self.model_dir, dtype=self.compute_dtype
                )
                mgr.ensure_head(
                    client["lm_head"], client.get("norm"),
                    self.spec.rms_norm_eps,
                )
                trainer = MidHeadTrainer(mgr._head)
            mgr.trainer = trainer
            self._pruner_manager = mgr
        except Exception as e:
            logger.warning("pruner unavailable: %s", e)
            self._pruner_unavailable = True

    def _ensure_pruner(self, threshold: float):
        if self._pruner_manager is None:
            return None
        self._pruner_manager.set_request_threshold(threshold)
        return self._pruner_manager

    async def _rpc_push(self, meta: dict, tensors) -> None:
        session = self._sessions.get(meta["session_id"])
        if meta.get("chain_ids") or meta.get("chain_error"):
            # chained-decode control message for a waiting coordinator:
            # bypass push_inbox (its consumer — the session loop — is
            # blocked inside the coordinator awaiting exactly this)
            if session is None:
                logger.warning(
                    "chain message for unknown session %s dropped",
                    meta["session_id"],
                )
                return
            session.chain_inbox.put_nowait((meta, tensors))
            return
        if session is None:
            # A push can race ahead of the session's stream-open (allocation
            # may be waiting on cache budget); buffer it briefly — the
            # reference accumulates early micro-batch pushes the same way
            # (handler.py:1850-2151 accumulate/immediate queues).
            self._buffer_pending_push(meta, tensors)
            return
        session.push_inbox.put_nowait((meta, tensors))

    def _buffer_pending_push(self, meta: dict, tensors) -> None:

        now = clock.monotonic()
        sid = meta["session_id"]
        self._pending_pushes.setdefault(sid, []).append((now, meta, tensors))
        # drop stale buffers
        for key in list(self._pending_pushes):
            self._pending_pushes[key] = [
                e
                for e in self._pending_pushes[key]
                if now - e[0] < self.pending_push_ttl
            ]
            if not self._pending_pushes[key]:
                del self._pending_pushes[key]

    def _drain_pending_pushes(self, session: _Session) -> None:
        for _, meta, tensors in self._pending_pushes.pop(session.id, []):
            session.push_inbox.put_nowait((meta, tensors))

    async def _rpc_forward(self, meta: dict, tensors):
        """Span forward without a session (training / one-shot),
        reference block_functions.py:247 run_rpc_forward."""
        if meta.get("audit"):
            # an integrity client re-executing another replica's recorded
            # step through us; count it so operators can see audit load
            self.audit_forwards += 1
        if self.training is None:
            raise RuntimeError("training path unavailable for this family")
        hidden = np.asarray(tensors[0], dtype=np.float32)
        prompts = (
            np.asarray(tensors[1], dtype=np.float32)
            if meta.get("deep_prompts") and len(tensors) > 1
            else None
        )
        layers = self._resolve_layers(meta)
        out = await self.compute.submit(
            PRIORITY_TRAINING, self.training.forward, hidden, layers, prompts,
            meta.get("adapter"),
        )
        if self.liar_p > 0 and self._liar_rng.random() < self.liar_p:
            # TEST HOOK: a Byzantine server lies on every plane — including
            # when another client drafts it as an audit replica (a lying
            # auditor must get outvoted by the tiebreak, not trusted)
            out = self._liar_perturb(out)
            self.liar_steps += 1
        return {"ok": True}, [out]

    async def _rpc_backward(self, meta: dict, tensors):
        """Gradient w.r.t. span inputs (blocks frozen; backward recomputes
        the forward — reference block_functions.py:357 run_rpc_backward)."""
        if self.training is None:
            raise RuntimeError("training path unavailable for this family")
        hidden_in = np.asarray(tensors[0], dtype=np.float32)
        grad_out = np.asarray(tensors[1], dtype=np.float32)
        prompts = (
            np.asarray(tensors[2], dtype=np.float32)
            if meta.get("deep_prompts") and len(tensors) > 2
            else None
        )
        layers = self._resolve_layers(meta)
        result = await self.compute.submit(
            PRIORITY_TRAINING, self.training.backward, hidden_in, grad_out,
            layers, prompts, meta.get("adapter"),
        )
        if prompts is not None:
            g_in, g_prompts = result
            return {"ok": True}, [g_in, g_prompts]
        return {"ok": True}, [result]
