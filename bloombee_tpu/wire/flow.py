"""Self-tuning concurrency limiter for server-to-server pushes.

Capability match for the reference's adaptive push concurrency
(/root/reference/src/bloombee/server/handler.py:255-370): bound the number
of in-flight pushes per peer and adapt the bound from runtime signals only —
no operator knob. The control law is AIMD-flavored:

- repeated send failures  -> shrink (stability first),
- waiters queue while sends stay fast -> grow (sender-side pressure, the
  link has headroom),
- sends slow down while nobody waits  -> shrink (network backpressure;
  more concurrency would only deepen the TCP queue).

Signals are EWMA-smoothed and decisions are made every `decide_every`
completions so one outlier can't flap the limit.
"""

from __future__ import annotations

import asyncio
import logging
import time

logger = logging.getLogger(__name__)


class FlowLimiter:
    def __init__(
        self,
        name: str = "",
        initial: int = 4,
        lo: int = 1,
        hi: int = 16,
        alpha: float = 0.2,
        decide_every: int = 8,
        wait_up_ms: float = 4.0,
        send_ok_ms: float = 100.0,
        send_slow_ms: float = 150.0,
    ):
        self.name = name
        self.lo, self.hi = int(lo), int(hi)
        self.limit = min(self.hi, max(self.lo, int(initial)))
        self._alpha = alpha
        self._decide_every = max(1, decide_every)
        self._wait_up_ms = wait_up_ms
        self._send_ok_ms = send_ok_ms
        self._send_slow_ms = send_slow_ms
        self.in_flight = 0
        self._cond = asyncio.Condition()
        self.ewma_wait_ms = 0.0
        self.ewma_send_ms = 0.0
        self._completions = 0
        self._consecutive_failures = 0

    def _ewma(self, prev: float, sample: float) -> float:
        return sample if prev <= 0.0 else prev * (1 - self._alpha) + sample * self._alpha

    def slot(self) -> "_Slot":
        """One bounded in-flight operation: `async with limiter.slot(): ...`.
        Each slot carries its own send-start time — concurrent holders must
        not share timing state, or slow sends get mismeasured against a
        later holder's start."""
        return _Slot(self)

    async def _acquire(self) -> float:
        t0 = time.perf_counter()
        async with self._cond:
            while self.in_flight >= self.limit:
                await self._cond.wait()
            self.in_flight += 1
        self.ewma_wait_ms = self._ewma(
            self.ewma_wait_ms, (time.perf_counter() - t0) * 1000.0
        )
        return time.perf_counter()

    async def _release(self, send_ms: float, ok: bool):
        async with self._cond:
            self.in_flight = max(0, self.in_flight - 1)
            self.ewma_send_ms = self._ewma(self.ewma_send_ms, send_ms)
            if ok:
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1
            self._completions += 1
            if self._completions % self._decide_every == 0:
                self._decide()
            self._cond.notify_all()

    def stats(self) -> dict:
        """Control-law observables (surfaced by the wire pipeline through
        rpc_info → health --probe)."""
        return {
            "limit": self.limit,
            "in_flight": self.in_flight,
            "ewma_wait_ms": round(self.ewma_wait_ms, 3),
            "ewma_send_ms": round(self.ewma_send_ms, 3),
        }

    def _decide(self) -> None:
        old = self.limit
        if self._consecutive_failures >= 2:
            self.limit = max(self.lo, self.limit - 1)
            self._consecutive_failures = 0
            reason = "failures"
        elif (
            self.ewma_wait_ms > self._wait_up_ms
            and self.ewma_send_ms < self._send_ok_ms
        ):
            self.limit = min(self.hi, self.limit + 1)
            reason = "queue_pressure"
        elif (
            self.ewma_send_ms > self._send_slow_ms
            and self.ewma_wait_ms < 1.0
        ):
            self.limit = max(self.lo, self.limit - 1)
            reason = "backpressure"
        else:
            return
        if self.limit != old:
            logger.info(
                "[flow] %s limit %d->%d (%s) wait=%.1fms send=%.1fms",
                self.name, old, self.limit, reason,
                self.ewma_wait_ms, self.ewma_send_ms,
            )


class _Slot:
    """Per-acquisition state for FlowLimiter (send start time lives here)."""

    def __init__(self, limiter: FlowLimiter):
        self._limiter = limiter
        self._t0 = 0.0

    async def __aenter__(self):
        self._t0 = await self._limiter._acquire()
        return self

    async def __aexit__(self, exc_type, exc, tb):
        send_ms = (time.perf_counter() - self._t0) * 1000.0
        await self._limiter._release(send_ms, ok=exc_type is None)
        return False  # never swallow the exception
