"""Per-scenario metrics and the metastable-failure convergence gates.

A metastable failure is a swarm that stays broken after its trigger
clears: shedding that never returns to baseline because retries feed the
very queue that sheds them, promotion loops that flap a standby in and
out, sessions starving while capacity sits idle. Point-in-time metrics
can't see these — they are properties of the TIME SERIES after the
perturbation — so a sampler records per-virtual-second counter snapshots
and the gates score the tail of the series.

Gate bounds are env-tunable (declared here, BB005 house style) so a
deliberately mis-tuned control plane — e.g. ``BBTPU_ADMIT_RETRY_MS=1``,
which turns every shed into an instant re-stampede — demonstrably FAILS
while the stock tuning passes: the anti-vacuity contract of
``python -m bloombee_tpu.sim --require``.
"""

from __future__ import annotations

import asyncio
import dataclasses

from bloombee_tpu.utils import clock, env

env.declare(
    "BBTPU_SIM_SETTLE_S", float, 45.0,
    "simulator gate: virtual seconds after a perturbation (flash-crowd "
    "end, crash, peak passing) by which the swarm's shed rate must have "
    "returned to zero — the metastability bound",
)
env.declare(
    "BBTPU_SIM_RETRY_AMP_MAX", float, 8.0,
    "simulator gate: maximum retry amplification (server-reaching "
    "session-open attempts divided by sessions) before the run counts "
    "as a retry storm",
)
env.declare(
    "BBTPU_SIM_SHED_AMP_MAX", float, 15.0,
    "simulator gate: maximum mean open attempts among sessions that got "
    "shed at least once — retry INTENSITY, scale-invariant where the "
    "overall amplification dilutes with background traffic volume",
)
env.declare(
    "BBTPU_SIM_FLAP_MAX", int, 6,
    "simulator gate: maximum promotion+demotion transitions per server "
    "per scenario before standby behavior counts as flapping",
)
env.declare(
    "BBTPU_SIM_PROMOTE_LATENCY_S", float, 30.0,
    "simulator gate: virtual seconds from span loss (crash) to the first "
    "standby promotion",
)


@dataclasses.dataclass
class Sample:
    t: float  # virtual seconds since scenario start
    shed: int  # cumulative shed_requests+shed_sessions across servers
    promotions: int
    demotions: int
    rebalances: int
    capacity_ok: bool


class Sampler:
    """Once per virtual second, snapshot the swarm's cumulative counters.
    Runs as a background task; the scenario cancels it after the session
    population completes."""

    def __init__(self, swarm, start_t: float):
        self.swarm = swarm
        self.start_t = start_t
        self.samples: list[Sample] = []

    def snap(self) -> None:
        shed = promos = demos = rebal = 0
        for s in self.swarm.servers.values():
            shed += s.admission.shed_requests + s.admission.shed_sessions
            promos += s.promotions
            demos += s.demotions + s.promotions_yielded
            rebal += s.rebalances_moved
        self.samples.append(Sample(
            t=clock.monotonic() - self.start_t,
            shed=shed, promotions=promos, demotions=demos,
            rebalances=rebal, capacity_ok=self.swarm.has_capacity_now(),
        ))

    async def run(self) -> None:
        while True:
            self.snap()
            await clock.async_sleep(1.0)


def percentile(xs: list, p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(p / 100.0 * len(xs)))
    return float(xs[i])


def last_shed_time(samples: list[Sample]) -> float:
    """Virtual time of the last sample interval in which anything shed."""
    last, prev = 0.0, 0
    for s in samples:
        if s.shed > prev:
            last = s.t
        prev = s.shed
    return last


def first_promotion_time(samples: list[Sample]) -> float | None:
    for s in samples:
        if s.promotions > 0:
            return s.t
    return None


def evaluate(
    name: str,
    results: list,
    samples: list[Sample],
    servers: dict,
    *,
    perturb_end_t: float | None = None,  # crowd end / crash / peak, in
    # scenario-relative virtual seconds; None = no settle gate
    expect_shed: bool = False,
    expect_promotion: bool = False,
    expect_rebalance: bool = False,
    min_complete_frac: float = 0.97,
) -> tuple[dict, list[str]]:
    """Score one scenario: (metrics json, gate-failure strings)."""
    settle_s = float(env.get("BBTPU_SIM_SETTLE_S"))
    amp_max = float(env.get("BBTPU_SIM_RETRY_AMP_MAX"))
    shed_amp_max = float(env.get("BBTPU_SIM_SHED_AMP_MAX"))
    flap_max = int(env.get("BBTPU_SIM_FLAP_MAX"))
    promote_max_s = float(env.get("BBTPU_SIM_PROMOTE_LATENCY_S"))

    ttfts = [r.ttft_s for r in results if r.ttft_s is not None]
    tbts = [x for r in results for x in r.tbts_s]
    n = max(1, len(results))
    completed = sum(r.completed for r in results)
    starved = sum(r.starved_with_capacity for r in results)
    attempts = sum(r.attempts for r in results)
    sheds = sum(r.sheds for r in results)
    amp = attempts / n
    shed_hit = [r for r in results if r.sheds > 0]
    shed_amp = (
        sum(r.attempts for r in shed_hit) / len(shed_hit)
        if shed_hit else 0.0
    )
    shed_end = last_shed_time(samples)
    promo_t = first_promotion_time(samples)

    flap = {
        sid: s.promotions + s.demotions + s.promotions_yielded
        for sid, s in servers.items()
    }
    counters = {sid: s.stats() for sid, s in servers.items()}
    total_shed = sum(
        s.admission.shed_requests + s.admission.shed_sessions
        for s in servers.values()
    )
    metrics = {
        "sessions": len(results),
        "completed": completed,
        "gave_up": sum(r.gave_up for r in results),
        "starved_with_capacity": starved,
        "ttft_p50_s": percentile(ttfts, 50),
        "ttft_p95_s": percentile(ttfts, 95),
        "tbt_p50_s": percentile(tbts, 50),
        "tbt_p95_s": percentile(tbts, 95),
        "shed_total": total_shed,
        "shed_rate_converged_at_s": shed_end,
        "retry_amplification": amp,
        "shed_retry_amplification": shed_amp,
        "session_sheds": sheds,
        "no_route_total": sum(r.no_route for r in results),
        "abandons": sum(r.abandons for r in results),
        "promotion_latency_s": (
            None if promo_t is None or perturb_end_t is None
            else max(0.0, promo_t - perturb_end_t)
        ),
        "promotions": sum(s.promotions for s in servers.values()),
        "demotions": sum(s.demotions for s in servers.values()),
        "rebalances_moved": sum(
            s.rebalances_moved for s in servers.values()
        ),
        "max_flap": max(flap.values()) if flap else 0,
    }

    failures: list[str] = []
    if starved:
        failures.append(
            f"{name}: {starved} session(s) starved past their deadline "
            "while swarm capacity existed"
        )
    if completed < min_complete_frac * len(results):
        failures.append(
            f"{name}: only {completed}/{len(results)} sessions completed "
            f"(gate {min_complete_frac:.0%})"
        )
    if amp > amp_max:
        failures.append(
            f"{name}: retry amplification {amp:.2f} exceeds "
            f"{amp_max:.2f} — retry storm (metastable)"
        )
    if shed_amp > shed_amp_max:
        failures.append(
            f"{name}: shed sessions averaged {shed_amp:.1f} open "
            f"attempts each (gate {shed_amp_max:.1f}) — under-hinted "
            "retries hammering the shedding swarm (metastable)"
        )
    if perturb_end_t is not None and shed_end > perturb_end_t + settle_s:
        failures.append(
            f"{name}: shedding still active {shed_end - perturb_end_t:.0f}s "
            f"after the perturbation cleared (settle bound {settle_s:.0f}s) "
            "— the swarm did not converge (metastable)"
        )
    worst_flap = max(flap.values()) if flap else 0
    if worst_flap > flap_max:
        failures.append(
            f"{name}: {worst_flap} promotion/demotion transitions on one "
            f"server (gate {flap_max}) — standby flapping"
        )
    if expect_shed and total_shed == 0:
        failures.append(
            f"{name}: expected overload shedding but none occurred — "
            "scenario lost its teeth (vacuous run)"
        )
    if expect_promotion:
        if metrics["promotions"] < 1:
            failures.append(
                f"{name}: expected a standby promotion but none happened"
            )
        elif (
            metrics["promotion_latency_s"] is not None
            and metrics["promotion_latency_s"] > promote_max_s
        ):
            failures.append(
                f"{name}: promotion took "
                f"{metrics['promotion_latency_s']:.0f}s "
                f"(gate {promote_max_s:.0f}s)"
            )
    if expect_rebalance and metrics["rebalances_moved"] < 1:
        failures.append(
            f"{name}: expected a measured-load rebalance move but none "
            "happened"
        )
    return {"metrics": metrics, "counters": counters}, failures


async def cancel_quietly(tasks: list) -> None:
    for t in tasks:
        t.cancel()
    for t in tasks:
        try:
            await t
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
