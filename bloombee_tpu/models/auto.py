"""Model family registry.

Port of /root/reference/src/bloombee/utils/auto_config.py:82-100: a registry
keyed by HF `model_type` dispatching config mapping, block param loading, and
client param names per family.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Callable

from bloombee_tpu.models.spec import ModelSpec

_REGISTRY: dict[str, "Family"] = {}


class Family:
    def __init__(
        self,
        name: str,
        spec_fn: Callable[[Any], ModelSpec],
        block_keys: dict[str, tuple[str, bool]] | None = None,
        layer_prefix: str = "model.layers",
        client_names: dict[str, str] | None = None,
        convert_block: Callable | None = None,
        loader: Callable | None = None,
        client_loader: Callable | None = None,
    ):
        self.name = name
        self._spec_fn = spec_fn
        self.block_keys = block_keys or {}
        self.layer_prefix = layer_prefix
        self._client_names = client_names or {
            "embed": "model.embed_tokens.weight",
            "norm": "model.norm.weight",
            "lm_head": "lm_head.weight",
        }
        self._convert_block = convert_block
        self._loader = loader
        self.client_loader = client_loader

    def spec_from_config_dict(self, config: dict) -> ModelSpec:
        return self._spec_fn(SimpleNamespace(**config))

    def client_param_names(self) -> dict[str, str]:
        return self._client_names

    def load_block_params(self, reader, layer_idx: int, dtype=None) -> dict:
        if self._loader is not None:
            return self._loader(reader, layer_idx, dtype=dtype)
        tensors = {}
        for hf_key in self.block_keys:
            full = f"{self.layer_prefix}.{layer_idx}.{hf_key}"
            tensors[hf_key] = reader.tensor(full)
        if self._convert_block is not None:
            return self._convert_block(tensors, dtype=dtype)
        raise NotImplementedError(self.name)


def register_family(family: Family) -> None:
    _REGISTRY[family.name] = family


def get_family(model_type: str) -> Family:
    if model_type not in _REGISTRY:
        raise KeyError(
            f"unknown model family {model_type!r}; known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[model_type]


def spec_from_hf_config(config: Any) -> ModelSpec:
    return get_family(config.model_type)._spec_fn(config)


def spec_from_config_dict(config: dict) -> ModelSpec:
    return get_family(config.get("model_type", "llama")).spec_from_config_dict(
        config
    )


# ---------------------------------------------------------------- built-ins
def _register_builtins() -> None:
    from bloombee_tpu.models.llama.block import (
        HF_BLOCK_KEYS as LLAMA_KEYS,
        convert_hf_block_params as llama_convert,
    )
    from bloombee_tpu.models.llama.config import llama_spec_from_hf

    register_family(
        Family(
            "llama",
            llama_spec_from_hf,
            LLAMA_KEYS,
            convert_block=llama_convert,
        )
    )
    # side-effect registrations
    import bloombee_tpu.models.bloom  # noqa: F401
    import bloombee_tpu.models.falcon  # noqa: F401
    import bloombee_tpu.models.gemma2  # noqa: F401
    import bloombee_tpu.models.gemma4  # noqa: F401
    import bloombee_tpu.models.mistral  # noqa: F401
    import bloombee_tpu.models.mixtral  # noqa: F401
    import bloombee_tpu.models.qwen2  # noqa: F401
    import bloombee_tpu.models.qwen3  # noqa: F401


_register_builtins()
