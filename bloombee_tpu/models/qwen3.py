"""Qwen3 family: Llama structure + per-head q/k RMSNorm + explicit head_dim.

Reference: /root/reference/src/bloombee/models/qwen3/ (WrappedQwen3Block).
152k vocab -> client-side head is the heavy part (README.md:103 note).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.llama.block import HF_BLOCK_KEYS, convert_hf_block_params
from bloombee_tpu.models.spec import ModelSpec


def qwen3_spec_from_hf(config: Any) -> ModelSpec:
    return ModelSpec(
        family="qwen3",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=getattr(config, "head_dim", None)
        or config.hidden_size // config.num_attention_heads,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 1000000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        qk_norm=True,
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    prefix = f"model.layers.{layer_idx}"
    tensors = {k: reader.tensor(f"{prefix}.{k}") for k in HF_BLOCK_KEYS}
    params = convert_hf_block_params(tensors, dtype=dtype)
    for name in ("q_norm", "k_norm"):
        w = jnp.asarray(reader.tensor(f"{prefix}.self_attn.{name}.weight"))
        params[name] = w.astype(dtype) if dtype is not None else w
    return params


register_family(
    Family("qwen3", qwen3_spec_from_hf, HF_BLOCK_KEYS, loader=_load_block)
)


# ---------------------------------------------------------------- qwen3-moe
def qwen3_moe_spec_from_hf(config: Any) -> ModelSpec:
    """Qwen3 attention (qk norms) + sparse MoE MLP. Router semantics are
    softmax-over-all-then-top-k, renormalized iff norm_topk_prob (HF
    Qwen3MoeSparseMoeBlock) — unlike Mixtral's mask-then-softmax."""
    import dataclasses

    if getattr(config, "mlp_only_layers", None) or getattr(
        config, "decoder_sparse_step", 1
    ) != 1:
        raise NotImplementedError(
            "qwen3-moe with dense interleaved layers (mlp_only_layers / "
            "decoder_sparse_step != 1) is not supported yet"
        )
    base = qwen3_spec_from_hf(config)
    return dataclasses.replace(
        base,
        family="qwen3_moe",
        intermediate_size=config.moe_intermediate_size,
        num_experts=config.num_experts,
        num_experts_per_tok=config.num_experts_per_tok,
        moe_pre_softmax=True,
        moe_norm_topk=bool(getattr(config, "norm_topk_prob", False)),
    )


def _load_block_moe(reader, layer_idx: int, dtype=None) -> dict:
    p = f"model.layers.{layer_idx}"
    from bloombee_tpu.models.checkpoint import read_tensor as _t

    params = {
        "input_layernorm": _t(reader, f"{p}.input_layernorm.weight", dtype),
        "post_attention_layernorm": _t(
            reader, f"{p}.post_attention_layernorm.weight", dtype
        ),
    }
    for proj in ("q", "k", "v", "o"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.self_attn.{proj}_proj.weight", dtype
        ).T
    for name in ("q_norm", "k_norm"):
        params[name] = _t(reader, f"{p}.self_attn.{name}.weight", dtype)
    params["router"] = _t(reader, f"{p}.mlp.gate.weight", dtype).T  # [D, E]
    from bloombee_tpu.models.checkpoint import stack_expert_weights

    params.update(
        stack_expert_weights(
            reader, f"{p}.mlp.experts.{{}}", "gate_proj", "up_proj",
            "down_proj", params["router"].shape[1], dtype,
        )
    )
    return params


register_family(
    Family("qwen3_moe", qwen3_moe_spec_from_hf, loader=_load_block_moe)
)
