"""ClientConfig: one dataclass for every client-side knob.

Port of /root/reference/src/bloombee/client/config.py:19-42 (timeouts,
retries/backoff, push-only downstream decode, allowed/blocked servers) —
round 1 scattered these across constructor kwargs; this consolidates them
and threads one object through model -> sequence manager -> sessions.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ClientConfig:
    # transport topology (reference use_server_to_server +
    # push_only_downstream_decode)
    use_push: bool = True
    # within-stage micro-batch count; "auto" sizes chunks to the pipeline
    # depth (reference microbatch_config derives it from the deployment);
    # None -> BBTPU_MICROBATCH env default
    microbatch: int | str | None = None
    # per-step failure handling (reference retries/backoff + ban_timeout):
    # each failure strike doubles the ban from ban_timeout up to ban_max
    # (with jitter); a success through the peer resets it
    max_retries: int = 3
    step_timeout: float = 120.0
    ban_timeout: float = 15.0
    ban_max: float = 120.0
    # routing view refresh (reference _SequenceManagerUpdateThread period)
    update_period: float = 5.0
    # server filters (reference allowed_servers / blocked_servers)
    allowed_servers: list[str] | None = None
    blocked_servers: list[str] | None = None
    # vocab-chunked LM head for low-RAM client hosts (reference
    # LMHead.chunked_forward, client/lm_head.py:50-76)
    use_chunked_head: bool = False
    chunked_head_step: int = 16384
    # per-request LoRA adapter: route only to servers announcing it and ask
    # them to apply it (reference config.py active_adapter + peft.py
    # using_adapter); None serves the base model
    active_adapter: str | None = None
    # opt-in server-side multi-step decode: when a greedy generate routes
    # through ONE span covering the whole model, ask the server to run
    # `server_decode_chunk` embed->span->head->select steps per RPC
    # (runtime/decode_loop.py), amortizing the per-token host<->device round
    # trip; servers that cannot (sub-span, sharded, no client params)
    # decline and the client falls back to per-step decoding
    server_decode: bool = False
    server_decode_chunk: int = 32
    # shared-prefix KV cache: probe servers' page pools before the first
    # prefill and ship only the uncached suffix (servers adopt pooled pages
    # for the matched prefix — kv/paged.py hash pool). None defers to the
    # BBTPU_PREFIX_CACHE env switch; servers with the cache off just report
    # zero matches, so leaving this on against a mixed swarm is safe
    prefix_cache: bool | None = None
    # standby KV replication interval in sealed pages: every N newly-sealed
    # pages each span's server ships them (kv_put) into a same-span
    # standby's prefix pool, so failover replays at most one interval plus
    # the unsealed tail. Needs prefix_cache; 0 disables; None defers to the
    # BBTPU_REPL_EVERY env switch. Swarms with no capable standby (old
    # servers, mismatched page_size/span) silently fall back to full replay
    kv_repl_every: int | None = None
    # load-aware routing: add each server's predicted queue delay (from its
    # live load advert) to the Dijkstra edge cost, steering new sessions
    # away from hot servers before they start shedding
    load_aware_routing: bool = True
    # overload penalty class (shorter than fault bans — a shedding server
    # is healthy, just hot): first shed backs the peer off overload_timeout
    # seconds, doubling per strike up to overload_max
    overload_timeout: float = 2.0
    overload_max: float = 15.0
    # how many retriable `overloaded` sheds one step tolerates before
    # surfacing the error (separate from max_retries — a shed is the swarm
    # working as designed, not a fault)
    overload_retries: int = 10
    # fair-share identity reported to servers' admission controllers; None
    # uses one id per client process so extra sessions can't dodge fairness
    client_id: str | None = None
    # reconnect-resume: after a stream failure, try to re-attach each
    # span's lease-parked session (resume: session_id on a fresh stream)
    # and retransmit the failed step under its ORIGINAL step id — servers
    # that already applied it answer from the recorded reply (at-most-once)
    # so the generation continues token-identical with ZERO prompt replay.
    # Declined resumes (lease expired, leases off, KV evicted) fall back to
    # the ordinary standby/full-replay recovery. None -> BBTPU_RESUME env
    resume: bool | None = None
    # how long one span's resume handshake may take before the client gives
    # up on the cheap path and full-replays (deliberately shorter than
    # step_timeout: resume races the lease clock)
    resume_timeout: float = 10.0
    # wire keepalive interval for the client side of every span connection
    # (ping on idle, declare dead after ~2.5x silence) so a partitioned
    # server is detected without waiting out step_timeout; None ->
    # BBTPU_KEEPALIVE_S env, 0 disables
    keepalive_s: float | None = None
    # Byzantine-robust serving (opt-in; off = byte-for-byte legacy
    # behavior): every received span output passes an inline sanity gate
    # (all-finite + activation-RMS envelope) plus out_digest verification
    # against digest-advertising servers; rejects strike the peer and heal
    # via the existing reroute+replay recovery. None -> BBTPU_INTEGRITY env
    integrity: bool | None = None
    # per-step probability of re-executing a recorded span step on a
    # DIFFERENT server covering the same blocks and tolerance-comparing
    # the outputs (never exact equality — honest replicas differ in ulps);
    # a confirmed mismatch triggers a third-replica tiebreak and the
    # outvoted peer enters quarantine. > 0 implies integrity for the
    # session. None -> BBTPU_AUDIT_P env
    audit_p: float | None = None
    # quarantine penalty class (integrity convictions): base/cap backoff
    # seconds — deliberately the longest class (a peer that LIED, vs
    # crashed) — and how many sanity-gate strikes convict
    quarantine_timeout: float = 600.0
    quarantine_max: float = 3600.0
    integrity_strike_limit: int = 2
