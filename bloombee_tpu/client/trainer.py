"""Client-side remote training: fault-tolerant sequential autograd + p-tuning.

Mirrors /root/reference/src/bloombee/client/sequential_autograd.py:25-278
(span-wise sequential_forward/sequential_backward with retries) and
ptune.py:21-80 (trainable prompt embeddings, frozen remote blocks). The
autograd "function" here is explicit: the local head/loss gradient comes
from jax.vjp, the chain gradient from rpc_backward span by span in reverse.
"""

from __future__ import annotations

import asyncio
import functools
import logging

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.wire.rpc import RpcError, connect

logger = logging.getLogger(__name__)


class RemoteSpanChain:
    """Forward/backward over the span chain via rpc_forward/rpc_backward."""

    def __init__(self, manager: RemoteSequenceManager, max_retries: int = 3,
                 adapter: str | None = None):
        self.manager = manager
        self.max_retries = max_retries
        self.adapter = adapter  # per-request LoRA (rides rpc meta)

    async def _call_span(self, span, method, tensors, deep_prompts=False):
        conn = await connect(span.server_info.host, span.server_info.port)
        try:
            meta = {"start": span.start, "end": span.end}
            if self.adapter:
                meta["adapter"] = self.adapter
            if deep_prompts:
                meta["deep_prompts"] = True
            _, out = await conn.call(method, meta, tensors)
            return out
        finally:
            await conn.close()

    async def forward(self, hidden: np.ndarray, deep_prompts=None):
        """Returns (output, ctx) where ctx holds per-span inputs for backward
        (reference sequential_forward's intermediate activation capture).
        `deep_prompts` [L_total, P, D] adds per-layer trainable prompts
        (reference ptune.py deep mode); each span receives its layer rows."""
        attempt = 0
        while True:
            await self.manager.update()
            route = self.manager.make_sequence()
            inputs = []
            try:
                h = hidden
                for span in route:
                    inputs.append(h)
                    tensors = [h]
                    if deep_prompts is not None:
                        tensors.append(
                            np.asarray(
                                deep_prompts[span.start:span.end],
                                dtype=np.float32,
                            )
                        )
                    (h,) = await self._call_span(
                        span, "rpc_forward", tensors,
                        deep_prompts=deep_prompts is not None,
                    )
                return h, (route, inputs)
            except (RpcError, OSError, asyncio.TimeoutError) as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                logger.warning("chain forward failed (%s); retrying", e)
                await self.manager.update(force=True)

    async def backward(self, ctx, grad_out: np.ndarray, deep_prompts=None):
        """Reversed-span gradient chain; retries re-route the failed span
        only (its input is captured in ctx). With deep_prompts, also
        returns the full [L_total, P, D] prompt gradient."""
        route, inputs = ctx
        g = grad_out
        g_deep = (
            np.zeros_like(np.asarray(deep_prompts, dtype=np.float32))
            if deep_prompts is not None
            else None
        )
        for span, h_in in zip(reversed(route), reversed(inputs)):
            attempt = 0
            while True:
                try:
                    tensors = [h_in, g]
                    if deep_prompts is not None:
                        tensors.append(
                            np.asarray(
                                deep_prompts[span.start:span.end],
                                dtype=np.float32,
                            )
                        )
                        g, g_p = await self._call_span(
                            span, "rpc_backward", tensors, deep_prompts=True
                        )
                        g_deep[span.start:span.end] += g_p
                    else:
                        (g,) = await self._call_span(
                            span, "rpc_backward", tensors
                        )
                    break
                except (RpcError, OSError, asyncio.TimeoutError) as e:
                    attempt += 1
                    if attempt > self.max_retries:
                        raise
                    logger.warning("span backward failed (%s); re-routing", e)
                    self.manager.ban_peer(span.peer_id)
                    await self.manager.update(force=True)
                    new_route = self.manager.make_sequence(span.start, span.end)
                    if len(new_route) != 1:
                        raise RpcError(
                            f"no single replacement span for "
                            f"[{span.start},{span.end})"
                        )
                    span = new_route[0]
        if deep_prompts is not None:
            return g, g_deep
        return g


def init_prompts(seed: int, n_prompt: int, d: int) -> jnp.ndarray:
    """Trainable prompt-embedding init shared by PTune and classification
    (reference ptune.py prompt init)."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n_prompt, d)).astype(np.float32) * 0.02)


def prepend_prompts(prompts, h_tok: np.ndarray) -> np.ndarray:
    """[B, S, D] token hidden -> [B, P+S, D] with the trainable prompts
    broadcast onto every row (the shallow-PTune composition)."""
    b = h_tok.shape[0]
    n_prompt = prompts.shape[0]
    return np.concatenate(
        [
            np.broadcast_to(
                np.asarray(prompts)[None], (b, n_prompt, h_tok.shape[-1])
            ),
            h_tok,
        ],
        axis=1,
    ).astype(np.float32)


def prompt_grad(g_in: np.ndarray, n_prompt: int) -> jnp.ndarray:
    """Prompt gradient from the chain-input gradient: the prompt rows'
    grads summed over the batch (prompts are shared across rows)."""
    return jnp.asarray(g_in[:, :n_prompt]).sum(axis=0)


@functools.partial(jax.jit, static_argnames=("eps", "norm_type"))
def _head_loss_and_grads(
    norm_w, norm_b, head_w_in, chain_out, target_ids, mask,
    eps: float, norm_type: str,
):
    """Loss + grads w.r.t. (lm_head, chain_out). Prompts receive their grad
    through chain_out's leading positions (handled by the caller)."""

    def loss_fn(head_w, h):
        from bloombee_tpu.ops import rms_norm
        from bloombee_tpu.ops.norms import layer_norm

        if norm_type == "ln":
            hn = layer_norm(h, norm_w, norm_b, eps)
        else:
            hn = rms_norm(h, norm_w, eps)
        logits = (hn @ head_w).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tgt = jnp.where(mask, target_ids, 0)
        token_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return -(token_lp * mask).sum() / jnp.maximum(mask.sum(), 1)

    loss, (g_head, g_out) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        head_w_in, chain_out
    )
    return loss, g_head, g_out


class PTuneTrainer:
    """Prompt-tuning against frozen remote blocks (reference PTuneMixin)."""

    def __init__(
        self,
        model: DistributedModelForCausalLM,
        n_prompt: int = 8,
        lr: float = 0.05,
        seed: int = 0,
        deep: bool = False,  # per-layer prompts (reference ptune deep mode)
    ):
        self.model = model
        self.chain = RemoteSpanChain(
            model.manager,
            adapter=getattr(model.config, "active_adapter", None),
        )
        self.n_prompt = n_prompt
        self.lr = lr
        d = model.spec.hidden_size
        self.prompts = init_prompts(seed, n_prompt, d)
        self.deep_prompts = (
            np.zeros(
                (model.spec.num_hidden_layers, n_prompt, d), np.float32
            )
            if deep
            else None
        )
        self.lm_head = model.params["lm_head"].astype(jnp.float32)

    async def train_step(
        self, input_ids: np.ndarray, target_ids: np.ndarray
    ) -> float:
        """One SGD step on (prompts, lm_head); targets -100 = ignored."""
        b, s = input_ids.shape
        h_tok = self.model.embed(input_ids)
        h_in = prepend_prompts(self.prompts, h_tok)

        chain_out, ctx = await self.chain.forward(
            h_in, deep_prompts=self.deep_prompts
        )

        target_full = np.full((b, self.n_prompt + s), -100, np.int64)
        target_full[:, self.n_prompt :] = target_ids
        mask = jnp.asarray(target_full >= 0)
        loss, g_head, g_out = _head_loss_and_grads(
            self.model.params["norm"],
            self.model.params.get("norm_bias"),
            self.lm_head,
            jnp.asarray(chain_out),
            jnp.asarray(np.maximum(target_full, 0)),
            mask,
            eps=self.model.spec.rms_norm_eps,
            norm_type=self.model.spec.norm_type,
        )

        if self.deep_prompts is not None:
            g_in, g_deep = await self.chain.backward(
                ctx, np.asarray(g_out), deep_prompts=self.deep_prompts
            )
            self.deep_prompts = self.deep_prompts - self.lr * g_deep
        else:
            g_in = await self.chain.backward(ctx, np.asarray(g_out))
        self.prompts = self.prompts - self.lr * prompt_grad(
            g_in, self.n_prompt
        )
        self.lm_head = self.lm_head - self.lr * g_head
        return float(loss)
