"""The jitted span step: all local blocks, one compiled function.

Equivalent of the reference's merged-pool inference step
(/root/reference/src/bloombee/server/backend.py:1368-1399
`_MergedInferenceStep` runs every local block in one pool call, and
backend.py:487-789 `inference_step` does select-cache -> mask -> forward ->
finalize per block). Here the whole span is a single `lax.scan` over stacked
block params; the paged KV arena rides the scan as per-layer xs/ys so XLA can
alias the donated buffers, and the attention mask is computed once from
positions + context lengths.

Shape discipline (SURVEY.md section 7 hard part #1): everything is padded to
static buckets — batch, step tokens T, and cache pages — and validity is
carried by `ctx_lens` / position masks. Out-of-bucket padding rows scatter to
out-of-bounds slots, which jax drops (`mode="drop"`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops.rotary import rotary_cos_sin
from bloombee_tpu.runtime.layer_body import layer_body, layer_body_ragged


def unpack_plan(plan: jax.Array, b: int, t: int, max_pages: int, num_layers: int):
    """Split the packed int32 plan array back into its parts.

    The plan packs [slots(B*T) | page_table(B*max_pages) | positions(B*T) |
    total_lens(B) | layer_active(L)] into one int32 vector so a step costs ONE
    host->device transfer for all control data (transfer latency dominates on
    DCN-attached hosts; cf. the reference's single metadata sidecar per
    request, handler.py rpc metadata). `layer_active` gates which of the
    server's layers run — a session entering mid-span (suffix sub-span
    routing, reference `spans_containing_block`) skips the leading layers.
    """
    o1 = b * t
    o2 = o1 + b * max_pages
    o3 = o2 + b * t
    o4 = o3 + b
    slots = plan[:o1]
    page_table = plan[o1:o2].reshape(b, max_pages)
    q_positions = plan[o2:o3].reshape(b, t)
    total_lens = plan[o3:o4]
    layer_active = plan[o4 : o4 + num_layers]
    return slots, page_table, q_positions, total_lens, layer_active


def pack_plan(slots, page_table, q_positions, total_lens, layer_active):
    import numpy as np

    return np.concatenate(
        [
            np.ravel(slots).astype(np.int32),
            np.ravel(page_table).astype(np.int32),
            np.ravel(q_positions).astype(np.int32),
            np.ravel(total_lens).astype(np.int32),
            np.ravel(layer_active).astype(np.int32),
        ]
    )


def pack_step_payload(h_pad, plan):
    """Host side: hidden + plan bitcast into ONE vector, so a serving step
    costs a single h2d transfer (transfer count, not size, dominates on
    DCN/tunnel-attached hosts — each transfer is ~4 ms here regardless of
    payload). The device side splits and bitcasts back (see
    span_step_packed_impl); verified little-endian-consistent between numpy
    views and XLA bitcast_convert_type on both CPU and TPU."""
    import numpy as np

    lane = np.uint16 if h_pad.dtype.itemsize == 2 else np.uint32
    return np.concatenate([h_pad.view(lane).ravel(), plan.view(lane).ravel()])


def unpack_step_payload(payload: jax.Array, b: int, t: int, d: int):
    """Device side of pack_step_payload: split one uint16/uint32 buffer back
    into (hidden [b, t, d], plan int32). uint16 lanes are bf16 hidden +
    int32 plan as low/high half pairs (little-endian, matching numpy views
    on both CPU and TPU)."""
    n_h = b * t * d
    if payload.dtype == jnp.uint16:
        hidden = lax.bitcast_convert_type(payload[:n_h], jnp.bfloat16)
        plan = lax.bitcast_convert_type(
            payload[n_h:].reshape(-1, 2), jnp.int32
        )
    else:
        hidden = lax.bitcast_convert_type(payload[:n_h], jnp.float32)
        plan = lax.bitcast_convert_type(payload[n_h:], jnp.int32)
    return hidden.reshape(b, t, d), plan


def span_step_packed_impl(
    stacked_params: dict,
    arena_k: jax.Array,
    arena_v: jax.Array,
    payload: jax.Array,  # uint16 (bf16 compute) or uint32 (f32 compute)
    tree_mask: jax.Array | None = None,
    lora: dict | None = None,  # per-request LoRA factors, leading dim L
    *,
    spec: ModelSpec,
    b: int,
    t: int,
    page_size: int,
    max_pages: int,
    use_tree_mask: bool = False,
    windows: tuple | None = None,
    use_flash: bool = False,
    use_paged: bool = False,
    resident: int | None = None,
    attn_topk: int = 0,
    t_real: int | None = None,
):
    """span_step over a pack_step_payload buffer (one h2d per step).

    `resident` (weight-offload mode): the params stack covers only the
    first `resident` of the arena's layers — scan over that prefix, write
    the updated slabs back into the full donated arena, and leave the
    offloaded layers' slabs untouched (they get their own layer_step calls
    with host-streamed weights)."""
    hidden, plan = unpack_step_payload(payload, b, t, spec.hidden_size)
    if resident is None:
        return span_step_impl(
            stacked_params, arena_k, arena_v, hidden, plan, tree_mask,
            lora=lora,
            spec=spec, page_size=page_size, max_pages=max_pages,
            use_tree_mask=use_tree_mask, windows=windows, use_flash=use_flash,
            use_paged=use_paged, attn_topk=attn_topk, t_real=t_real,
        )
    hidden, ak, av = span_step_impl(
        stacked_params, arena_k[:resident], arena_v[:resident], hidden, plan,
        tree_mask, lora=lora,
        spec=spec, page_size=page_size, max_pages=max_pages,
        use_tree_mask=use_tree_mask, windows=windows, use_flash=use_flash,
        use_paged=use_paged, attn_topk=attn_topk, t_real=t_real,
    )
    arena_k = jax.lax.dynamic_update_slice_in_dim(arena_k, ak, 0, 0)
    arena_v = jax.lax.dynamic_update_slice_in_dim(arena_v, av, 0, 0)
    return hidden, arena_k, arena_v


span_step_packed = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "b", "t", "page_size", "max_pages", "use_tree_mask",
        "windows", "use_flash", "use_paged", "resident", "attn_topk",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(span_step_packed_impl)


def span_step_impl(
    stacked_params: dict,  # pytree, leading dim L on every leaf
    arena_k: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    arena_v: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    hidden: jax.Array,  # [B, T, D]
    plan: jax.Array,  # packed int32 (see unpack_plan)
    tree_mask: jax.Array | None = None,  # [B, T, T] bool
    prompts: jax.Array | None = None,  # [L, P, D] deep p-tuning prompts
    lora: dict | None = None,  # {proj: {a: [L,in,r], b: [L,r,out]}}
    *,
    spec: ModelSpec,
    page_size: int,
    max_pages: int,
    use_tree_mask: bool = False,
    windows: tuple | None = None,
    use_flash: bool = False,
    use_paged: bool = False,
    attn_topk: int = 0,
    t_real: int | None = None,
):
    """Run all local blocks over one step; returns (hidden, arena_k, arena_v).

    Rotary cos/sin are computed on-device from the plan's positions (no
    per-step host tables), in fp32 like HF. `prompts` adds a trainable
    per-layer vector to the first P positions of each ACTIVE layer's input
    (deep p-tuning — reference ptune.py:21-80 deep mode); inactive layers'
    rows are ignored.
    """
    b, t, _ = hidden.shape
    num_layers = arena_k.shape[0]
    slots, page_table, q_positions, total_lens, layer_active = unpack_plan(
        plan, b, t, max_pages, num_layers
    )
    cos, sin = rotary_cos_sin(q_positions, spec.head_dim, spec.rope_theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)
    if spec.rope_local_theta and spec.rope_local_theta != spec.rope_theta:
        # gemma3-style: sliding layers rope with the local base frequency;
        # the per-layer window (already riding the scan) selects the pair
        cos_loc, sin_loc = rotary_cos_sin(
            q_positions, spec.head_dim, spec.rope_local_theta
        )
        cos_loc = cos_loc.astype(hidden.dtype)
        sin_loc = sin_loc.astype(hidden.dtype)
    else:
        cos_loc, sin_loc = cos, sin

    tm = tree_mask if use_tree_mask else None
    windows_arr = jnp.asarray(
        windows if windows is not None else (0,) * num_layers, jnp.int32
    )

    xs = (stacked_params, arena_k, arena_v, layer_active, windows_arr)
    if prompts is not None:
        xs = xs + (prompts,)
    if lora is not None:
        xs = xs + (lora,)

    def body(h, xs):
        params_l, k_l, v_l, active, window_l = xs[:5]
        rest = list(xs[5:])
        prompt_l = rest.pop(0) if prompts is not None else None
        lora_l = rest.pop(0) if lora is not None else None
        use_local = window_l > 0
        cos_l = jnp.where(use_local, cos_loc, cos)
        sin_l = jnp.where(use_local, sin_loc, sin)

        def run(h, k_l, v_l):
            if prompt_l is not None:
                p = prompt_l.shape[0]
                h = h.at[:, :p].add(prompt_l[None].astype(h.dtype))
            return layer_body(
                spec, page_size, h, params_l, k_l, v_l, cos_l, sin_l, slots,
                page_table, q_positions, total_lens, tm, window_l,
                use_flash=use_flash, use_paged=use_paged, lora=lora_l,
                attn_topk=attn_topk, t_real=t_real,
            )

        def skip(h, k_l, v_l):
            return h, k_l, v_l

        h, k_l, v_l = lax.cond(active > 0, run, skip, h, k_l, v_l)
        return h, (k_l, v_l)

    hidden, (arena_k, arena_v) = lax.scan(body, hidden, xs)
    return hidden, arena_k, arena_v


span_step = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "page_size", "max_pages", "use_tree_mask", "windows",
        "use_flash", "use_paged", "attn_topk",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(span_step_impl)


def unpack_ragged_plan(
    plan: jax.Array, r: int, n_seqs: int, max_pages: int, num_layers: int,
    t_max: int = 0,
):
    """unpack_plan for the ragged mixed-batch step: token-axis vectors are
    [R] (one entry per ragged token row) and sequence-axis vectors are
    [n_seqs], tied together by q_seq — [slots(R) | page_table(B*max_pages)
    | positions(R) | total_lens(B) | q_seq(R) | layer_active(L)]. A ragged
    TREE-verify group (t_max > 0) appends two more segments:
    [... | nt(B) | tree_rows(R*t_max)] — nt[b] is sequence b's in-step
    (speculative) token count and tree_rows[i, m] says whether token row i
    may attend the m-th in-step token of its own sequence."""
    o1 = r
    o2 = o1 + n_seqs * max_pages
    o3 = o2 + r
    o4 = o3 + n_seqs
    o5 = o4 + r
    slots = plan[:o1]
    page_table = plan[o1:o2].reshape(n_seqs, max_pages)
    q_positions = plan[o2:o3].reshape(1, r)
    total_lens = plan[o3:o4]
    q_seq = plan[o4:o5]
    o6 = o5 + num_layers
    layer_active = plan[o5:o6]
    if not t_max:
        return (
            slots, page_table, q_positions, total_lens, q_seq, layer_active,
            None, None,
        )
    o7 = o6 + n_seqs
    nt = plan[o6:o7]
    tree_rows = plan[o7 : o7 + r * t_max].reshape(r, t_max)
    return (
        slots, page_table, q_positions, total_lens, q_seq, layer_active,
        nt, tree_rows,
    )


def pack_ragged_plan(
    slots, page_table, q_positions, total_lens, q_seq, layer_active,
    nt=None, tree_rows=None,
):
    import numpy as np

    parts = [
        np.ravel(slots).astype(np.int32),
        np.ravel(page_table).astype(np.int32),
        np.ravel(q_positions).astype(np.int32),
        np.ravel(total_lens).astype(np.int32),
        np.ravel(q_seq).astype(np.int32),
        np.ravel(layer_active).astype(np.int32),
    ]
    if nt is not None:
        parts.append(np.ravel(nt).astype(np.int32))
        parts.append(np.ravel(tree_rows).astype(np.int32))
    return np.concatenate(parts)


def span_step_ragged_impl(
    stacked_params: dict,
    arena_k: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    arena_v: jax.Array,
    payload: jax.Array,  # uint16 (bf16 compute) or uint32 (f32 compute)
    lora: dict | None = None,
    *,
    spec: ModelSpec,
    r: int,  # ragged token bucket (pow2-padded sum of member tokens)
    n_seqs: int,  # sequence bucket (pow2-padded member sequence count)
    page_size: int,
    max_pages: int,
    windows: tuple | None = None,
    use_kernel: bool = False,
    t_max: int = 0,
):
    """The ragged mixed-batch span step: N single-token decode members plus
    one prefill-chunk member packed into ONE [1, R, D] dispatch (the
    Sarathi-Serve fused iteration). Rides pack_step_payload as a b=1, t=R
    hidden; per-row (q_seq, q_positions) carry the member structure the
    block shapes no longer do. t_max > 0 switches the step into the ragged
    TREE-verify variant: the plan carries per-sequence in-step counts and
    per-row tree visibility, so N sessions' speculative trees verify in one
    dispatch. No prompts or offload-resident splits here — those step types
    stay on their dedicated paths (the executor gates eligibility
    host-side)."""
    hidden, plan = unpack_step_payload(payload, 1, r, spec.hidden_size)
    num_layers = arena_k.shape[0]
    (
        slots, page_table, q_positions, total_lens, q_seq, layer_active,
        nt, tree_rows,
    ) = unpack_ragged_plan(plan, r, n_seqs, max_pages, num_layers, t_max)
    cos, sin = rotary_cos_sin(q_positions, spec.head_dim, spec.rope_theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)
    if spec.rope_local_theta and spec.rope_local_theta != spec.rope_theta:
        cos_loc, sin_loc = rotary_cos_sin(
            q_positions, spec.head_dim, spec.rope_local_theta
        )
        cos_loc = cos_loc.astype(hidden.dtype)
        sin_loc = sin_loc.astype(hidden.dtype)
    else:
        cos_loc, sin_loc = cos, sin

    windows_arr = jnp.asarray(
        windows if windows is not None else (0,) * num_layers, jnp.int32
    )
    xs = (stacked_params, arena_k, arena_v, layer_active, windows_arr)
    if lora is not None:
        xs = xs + (lora,)

    def body(h, xs):
        params_l, k_l, v_l, active, window_l = xs[:5]
        lora_l = xs[5] if lora is not None else None
        use_local = window_l > 0
        cos_l = jnp.where(use_local, cos_loc, cos)
        sin_l = jnp.where(use_local, sin_loc, sin)

        def run(h, k_l, v_l):
            return layer_body_ragged(
                spec, page_size, h, params_l, k_l, v_l, cos_l, sin_l,
                slots, page_table, q_positions, total_lens, q_seq,
                window_l, use_kernel=use_kernel, lora=lora_l,
                nt=nt, tree_rows=tree_rows,
            )

        def skip(h, k_l, v_l):
            return h, k_l, v_l

        h, k_l, v_l = lax.cond(active > 0, run, skip, h, k_l, v_l)
        return h, (k_l, v_l)

    hidden, (arena_k, arena_v) = lax.scan(body, hidden, xs)
    return hidden, arena_k, arena_v


span_step_ragged = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "r", "n_seqs", "page_size", "max_pages", "windows",
        "use_kernel", "t_max",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(span_step_ragged_impl)


def layer_step_impl(
    params_l: dict,  # ONE layer's params (no leading L dim)
    arena_k: jax.Array,  # [L, S_tot, Hkv, hd] (donated; updated at layer_idx)
    arena_v: jax.Array,
    hidden: jax.Array,  # [B, T, D]
    plan: jax.Array,  # packed with ONE layer_active entry
    layer_idx: jax.Array,  # traced i32 scalar: which arena slab to touch
    tree_mask: jax.Array | None = None,
    lora_l: dict | None = None,
    *,
    spec: ModelSpec,
    page_size: int,
    max_pages: int,
    use_tree_mask: bool = False,
    window: int = 0,  # static per-layer window (<= 2 distinct compiles)
    use_flash: bool = False,
    use_paged: bool = False,
    attn_topk: int = 0,
    t_real: int | None = None,
):
    """One layer of the span as its own compiled step — the unit of the
    weight-offload path (reference FlexGen Policy weight percentages /
    convert_block.py PipelineParallelWrapper pre-forward H2D): offloaded
    layers' params arrive from host per step, so they can't ride the
    resident stack's scan. The layer's K/V slab is read out of and written
    back into the DONATED arena in place (dynamic_update_index aliases the
    buffer), so the persistent KV state never leaves the device."""
    b, t, _ = hidden.shape
    slots, page_table, q_positions, total_lens, _ = unpack_plan(
        plan, b, t, max_pages, 1
    )
    local = bool(
        window > 0
        and spec.rope_local_theta
        and spec.rope_local_theta != spec.rope_theta
    )
    theta = spec.rope_local_theta if local else spec.rope_theta
    cos, sin = rotary_cos_sin(q_positions, spec.head_dim, theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)
    k_l = jax.lax.dynamic_index_in_dim(arena_k, layer_idx, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(arena_v, layer_idx, 0, keepdims=False)
    hidden, k_l, v_l = layer_body(
        spec, page_size, hidden, params_l, k_l, v_l, cos, sin, slots,
        page_table, q_positions, total_lens,
        tree_mask if use_tree_mask else None,
        jnp.int32(window),
        use_flash=use_flash, use_paged=use_paged, lora=lora_l,
        attn_topk=attn_topk, t_real=t_real,
    )
    arena_k = jax.lax.dynamic_update_index_in_dim(arena_k, k_l, layer_idx, 0)
    arena_v = jax.lax.dynamic_update_index_in_dim(arena_v, v_l, layer_idx, 0)
    return hidden, arena_k, arena_v


layer_step = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "page_size", "max_pages", "use_tree_mask", "window",
        "use_flash", "use_paged", "attn_topk",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(layer_step_impl)
