"""Flash attention (Pallas TPU kernel): causal/full, GQA, fp32 accumulation.

Replaces the reference's prefill attention kernel
(/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:665
`mha_llama`) for long sequences: attention logits never hit HBM, and K/V
stream through VMEM one [block_k, hd] tile at a time (third grid dimension)
with online-softmax stats (m, l, acc) carried in VMEM scratch across the
K-tile steps — so VMEM residency is O(block) regardless of sequence length.

Row r's query i sits at absolute position `starts[r] + i`; keys occupy
absolute positions 0..S-1 and row r sees keys below `lens[r]`. starts/lens
are *traced* per-row vectors (scalar-prefetch inputs), so chunked prefill
at varying — and MIXED — start positions reuses one compiled kernel: a
batch whose rows carry different committed context lengths (multi-turn
session prefill) runs flash instead of falling back to the dense gather
(round-4 verdict #10). The per-row lens mask also hides the garbage tail
of a gathered page run (the serving path gathers whole pages, so S is the
page-aligned bucket, not the exact context length), and K blocks wholly
past a row's lens are skipped outright. The uniform-offset API remains as
`offset=` sugar.

Callers that need tree masks / ALiBi / sliding windows / soft-capping use
`ops.attention.masked_attention`; the serving executor picks per step
(CPU tests run this kernel in interpreter mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _kernel(
    starts_ref,  # [B] i32 scalar prefetch: absolute position of each
    # row's query 0 (rows may differ — mixed-length batches)
    lens_ref,  # [B] i32 scalar prefetch: per-row visible key count
    q_ref,  # [block_q, hd]
    k_ref,  # [block_k, hd] (current K tile)
    v_ref,  # [block_k, hd]
    o_ref,  # [block_q, hd]
    m_scr,  # [block_q, 1] f32 scratch
    l_scr,  # [block_q, 1] f32 scratch
    acc_scr,  # [block_q, hd] f32 scratch
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    n_k: int,
    h: int,  # query heads (grid dim 0 is b*h; b_idx = bh // h)
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    b_idx = bh // h

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    offset = starts_ref[b_idx]
    length = lens_ref[b_idx]
    q_pos = (
        offset
        + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )
    # highest absolute query position in this q block; K blocks wholly
    # past this row's length cost neither compute nor (via the index-map
    # clamp) HBM bandwidth
    q_max = offset + qi * block_q + block_q - 1
    block_visible = (kj * block_k < length) & (
        jnp.bool_(True) if not causal else (kj * block_k <= q_max)
    )

    @pl.when(block_visible)
    def _update():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        mask = jnp.broadcast_to(k_pos < length, (block_q, block_k))
        if causal:
            mask = mask & (k_pos <= q_pos)
        logits = jnp.where(mask, logits, NEG)
        pmask = mask.astype(jnp.float32)
        m = m_scr[...]
        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new) * pmask
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + p.sum(axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(kj == n_k - 1)
    def _finalize():
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,  # [B, T, H, hd]
    k: jax.Array,  # [B, S, Hkv, hd], S >= T (extra = committed prefix)
    v: jax.Array,  # [B, S, Hkv, hd]
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    offset=None,  # traced i32 scalar, uniform-start sugar; None and no
    # starts => S - T (queries at the end)
    starts=None,  # [B] traced i32: per-row absolute position of query 0
    # (mixed-length batches); overrides offset
    lens=None,  # [B] traced i32: per-row visible key count; None =>
    # starts + T when causal (exactly the keys the causal mask would
    # allow), else S (non-causal attends everything, as before)
) -> jax.Array:
    b, t, h, hd = q.shape
    s, hkv = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"H={h} must be a multiple of Hkv={hkv}")
    if s < t:
        raise ValueError(f"S={s} must be >= T={t}")
    n_rep = h // hkv
    if scale is None:
        scale = hd**-0.5
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        raise ValueError(
            f"seq lens must divide blocks: T={t}%{block_q}, S={s}%{block_k}"
        )
    n_k = s // block_k
    if starts is None:
        starts = jnp.full((b,), s - t if offset is None else offset)
    starts = jnp.asarray(starts, jnp.int32).reshape(b)
    if lens is None:
        lens = starts + t if causal else jnp.full((b,), s)
    lens = jnp.asarray(lens, jnp.int32).reshape(b)

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, hd)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, hd)

    def kv_index(bh, qi, kj, st, ln):
        # K blocks past this row's visible range must not cost HBM
        # bandwidth: clamp dead steps onto the last visible block so
        # Pallas elides the duplicate DMA (their compute is skipped by
        # pl.when(block_visible) in the kernel)
        last = ln[bh // h] - 1
        if causal:
            q_max = st[bh // h] + qi * block_q + block_q - 1
            last = jnp.minimum(last, q_max)
        last_blk = jnp.maximum(last, 0) // block_k
        return (bh // n_rep, jnp.minimum(kj, last_blk), 0)

    grid = (b * h, t // block_q, n_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (None, block_q, hd), lambda bh, qi, kj, st, ln: (bh, qi, 0)
            ),
            pl.BlockSpec((None, block_k, hd), kv_index),
            pl.BlockSpec((None, block_k, hd), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, hd), lambda bh, qi, kj, st, ln: (bh, qi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            n_k=n_k,
            h=h,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, t, hd), q.dtype),
        interpret=interpret,
    )(starts, lens, qf, kf, vf)
    return out.reshape(b, h, t, hd).transpose(0, 2, 1, 3)
