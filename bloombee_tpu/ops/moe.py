"""Mixture-of-experts MLP (Mixtral family).

The reference runs all 8 experts densely inside one HF block with NO expert
parallelism (SURVEY.md section 2.3 Mixtral row, 2.8: "EP is absent"). Here
the experts are stacked weight tensors so the whole MoE layer is a few
einsums — dense over experts, masked by top-k router weights — which tiles
onto the MXU, and the expert dimension shards over the mesh for real expert
parallelism (bloombee_tpu/parallel/spmd.py psums the partial outputs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def router_topk_weights(
    logits: jax.Array,  # [B, T, E]
    top_k: int,
    pre_softmax: bool = False,
    norm_topk: bool = False,
) -> jax.Array:
    """Top-k router weights, zero off the selected experts.

    pre_softmax=False: HF Mixtral semantics — mask to the top-k logits,
    then softmax over them. pre_softmax=True: HF Qwen3-MoE semantics —
    softmax over ALL experts, select top-k, renormalize iff norm_topk."""
    if pre_softmax:
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_vals, _ = jax.lax.top_k(probs, top_k)
        kept = jnp.where(probs >= top_vals[..., -1:], probs, 0.0)
        if norm_topk:
            kept = kept / jnp.maximum(
                kept.sum(axis=-1, keepdims=True), 1e-20
            )
        return kept.astype(logits.dtype)
    top_vals, _ = jax.lax.top_k(logits, top_k)
    thresh = top_vals[..., -1:]
    neg = jnp.finfo(jnp.float32).min
    masked = jnp.where(logits >= thresh, logits.astype(jnp.float32), neg)
    return jax.nn.softmax(masked, axis=-1).astype(logits.dtype)  # [B, T, E]


def moe_mlp(
    x: jax.Array,  # [B, T, D]
    router_w: jax.Array,  # [D, E]
    gate_w: jax.Array,  # [E, D, I]
    up_w: jax.Array,  # [E, D, I]
    down_w: jax.Array,  # [E, I, D]
    top_k: int,
    router_weights: jax.Array | None = None,  # precomputed [B, T, E]
    pre_softmax: bool = False,
    norm_topk: bool = False,
) -> jax.Array:
    """Dense-over-experts gated MLP weighted by top-k router probabilities.

    When experts are sharded, pass `router_weights` computed from the full
    router and slice gate/up/down to the local experts; sum partial outputs
    with psum outside.
    """
    if router_weights is None:
        logits = x @ router_w
        router_weights = router_topk_weights(
            logits, top_k, pre_softmax=pre_softmax, norm_topk=norm_topk
        )
    g = jnp.einsum("btd,edi->btei", x, gate_w)
    u = jnp.einsum("btd,edi->btei", x, up_w)
    h = jax.nn.silu(g) * u
    out = jnp.einsum("btei,eid->bted", h, down_w)
    return jnp.einsum("bted,bte->btd", out, router_weights)
