"""Scenario catalog: swarm topologies + workloads + scripted faults.

Each scenario builds a small swarm (every server runs the real control
plane — see node.py), generates ≥1000 seeded virtual sessions, scripts
its perturbation, drives the whole thing under the discrete-event engine,
and scores the time series with metrics.evaluate. Horizons scale with the
session count so arrival RATES — the thing the control plane actually
responds to — are identical between the CI-sized run and a quick smoke.

  flash_crowd  an absolute-size crowd of naive gateway sessions lands
               inside a seconds-wide window on a two-span swarm with one
               [4:8) standby: admission must shed, the standby may
               promote, and shedding must CONVERGE after the crowd
               passes even though abandoned first-token timeouts leave
               zombie prefills burning (the metastable-retry gate).
  span_loss    correlated failure: the [4:8) primary crashes at a
               scripted decode step (wire/faults.py FaultSchedule), its
               replica dies 5 virtual seconds later under the failover
               load; the standby must promote within the latency gate and
               every stranded session must recover.
  diurnal      a day-long sine ramp over a swarm whose [4:8) server is a
               slow host (16x compute, nominal advert): at peak the
               measured-load rebalancer must MOVE the spare [0:4) replica
               onto the hot span, and shedding must die with the peak.
"""

from __future__ import annotations

import asyncio
import random

from bloombee_tpu.sim import metrics as sim_metrics
from bloombee_tpu.sim.client import SimSwarm, run_session
from bloombee_tpu.sim.cost import CostModel
from bloombee_tpu.sim.node import SimServer
from bloombee_tpu.sim.workload import (
    diurnal_sessions,
    flash_crowd_sessions,
    poisson_sessions,
)
from bloombee_tpu.swarm.registry import InProcessRegistry
from bloombee_tpu.utils import clock, env
from bloombee_tpu.wire.faults import FaultSchedule, ScheduledFault

MODEL_UID = "sim-model"
NUM_BLOCKS = 8
BASE_PORT = 4200

env.declare(
    "BBTPU_SIM_SESSIONS", int, 1000,
    "virtual sessions per simulator scenario (the --require CI gate "
    "runs this many; --smoke drops to ~200 for bench/chaos rides)",
)
env.declare(
    "BBTPU_SIM_SEED", int, 0,
    "base RNG seed for simulator workload generation and routing jitter "
    "— same seed, same sessions, same verdict",
)
env.declare(
    "BBTPU_SIM_WALL_BUDGET_S", float, 110.0,
    "real-seconds budget per simulator scenario; a scenario that cannot "
    "finish its virtual timeline inside it fails as stalled",
)


def _mk(engine, swarm, faults, sid, start, end, port_off, **kw):
    server = SimServer(
        engine, swarm.registry, MODEL_UID, sid, start, end, NUM_BLOCKS,
        swarm.cost, port=BASE_PORT + port_off, faults=faults, **kw,
    )
    swarm.add(server)
    return server


async def _drive(engine, swarm, specs, seed, horizon_s):
    """Start the swarm, run the session population to completion under
    the conductor, tear down, and hand back (results, samples)."""
    start_t = clock.monotonic()
    for s in swarm.servers.values():
        s.start()
    sampler = sim_metrics.Sampler(swarm, start_t)
    sampler_task = asyncio.create_task(sampler.run())

    rng = random.Random(seed)
    managers: dict = {}
    tasks = []
    for spec in specs:
        sm = managers.get(spec.client_id)
        if sm is None:
            sm = swarm.make_manager(
                rng=random.Random(rng.random())
            )
            managers[spec.client_id] = sm
        tasks.append(asyncio.create_task(run_session(swarm, sm, spec)))

    await engine.run_tasks(
        tasks,
        max_virtual_s=horizon_s + 600.0,
        max_wall_s=float(env.get("BBTPU_SIM_WALL_BUDGET_S")),
    )
    sampler.snap()
    await sim_metrics.cancel_quietly([sampler_task])
    await sim_metrics.cancel_quietly(swarm.zombies)
    for s in swarm.servers.values():
        s.stop()
        await sim_metrics.cancel_quietly(s._tasks)
    return [t.result() for t in tasks], sampler.samples, start_t


def _new_swarm(cost=None) -> SimSwarm:
    return SimSwarm(
        InProcessRegistry(), MODEL_UID, NUM_BLOCKS,
        cost or CostModel.from_env(num_blocks=NUM_BLOCKS),
    )


# ------------------------------------------------------------- flash crowd
async def flash_crowd(engine, sessions: int, seed: int) -> dict:
    horizon = max(120.0, 0.6 * sessions)
    swarm = _new_swarm()
    faults = FaultSchedule([])
    _mk(engine, swarm, faults, "a0", 0, 4, 0)
    _mk(engine, swarm, faults, "b0", 4, 8, 3)
    _mk(engine, swarm, faults, "sb", 4, 8, 6, standby=True)
    crowd_at = horizon * 0.4
    crowd_width = 3.0  # absolute, like the crowd itself: an impulse
    specs = flash_crowd_sessions(
        sessions, horizon, seed=seed, crowd_at_s=crowd_at,
        crowd_width_s=crowd_width,
    )
    results, samples, _ = await _drive(engine, swarm, specs, seed, horizon)
    report, failures = sim_metrics.evaluate(
        "flash_crowd", results, samples, swarm.servers,
        perturb_end_t=crowd_at + crowd_width, expect_shed=True,
    )
    return {**report, "failures": failures}


# --------------------------------------------------------------- span loss
async def span_loss(engine, sessions: int, seed: int) -> dict:
    horizon = max(120.0, 0.6 * sessions)
    swarm = _new_swarm()
    # the primary dies at a scripted decode step — the logical-clock
    # vocabulary chaos e2e tests use (ScheduledFault counts span-output
    # replies on that server's port)
    faults = FaultSchedule([
        ScheduledFault(
            at_step=max(120, int(600 * sessions / 1000)),
            action="crash", port=BASE_PORT + 3, target="b0",
        ),
    ])
    _mk(engine, swarm, faults, "a0", 0, 4, 0)
    b0 = _mk(engine, swarm, faults, "b0", 4, 8, 3)
    b1 = _mk(engine, swarm, faults, "b1", 4, 8, 4)
    _mk(engine, swarm, faults, "sb", 4, 8, 6, standby=True)

    async def correlated_second_crash():
        # the replica absorbs the failover load for 5 virtual seconds,
        # then dies too (shared rack / shared bug — the correlated case
        # that makes the standby the span's only hope)
        while not b0._crashed:
            await clock.async_sleep(1.0)
        await clock.async_sleep(5.0)
        b1.crash()

    watcher = asyncio.create_task(correlated_second_crash())
    specs = poisson_sessions(sessions, horizon, seed=seed)
    results, samples, start_t = await _drive(
        engine, swarm, specs, seed, horizon
    )
    await sim_metrics.cancel_quietly([watcher])
    crash_rel = max(
        (s.crashed_at - start_t)
        for s in (b0, b1) if s.crashed_at is not None
    ) if b0.crashed_at or b1.crashed_at else None
    report, failures = sim_metrics.evaluate(
        "span_loss", results, samples, swarm.servers,
        perturb_end_t=crash_rel, expect_promotion=True,
        min_complete_frac=0.95,
    )
    if not (b0._crashed and b1._crashed):
        failures.append(
            "span_loss: scripted crashes never fired (fault schedule "
            "never came due) — vacuous run"
        )
    return {**report, "failures": failures}


# ----------------------------------------------------------------- diurnal
async def diurnal(engine, sessions: int, seed: int) -> dict:
    horizon = max(120.0, 0.6 * sessions)
    swarm = _new_swarm()
    faults = FaultSchedule([])
    _mk(engine, swarm, faults, "a0", 0, 4, 0)
    # a1 is the spare capacity the rebalancer may move
    _mk(engine, swarm, faults, "a1", 0, 4, 1, rebalance_period=7.0)
    # b0 is a slow host: 16x the modeled compute cost, nominal advert —
    # only its live load advert (measured rebalancing) exposes it
    _mk(engine, swarm, faults, "b0", 4, 8, 3, cost_scale=16.0)
    specs = diurnal_sessions(sessions, horizon, seed=seed)
    results, samples, _ = await _drive(engine, swarm, specs, seed, horizon)
    report, failures = sim_metrics.evaluate(
        "diurnal", results, samples, swarm.servers,
        perturb_end_t=horizon * 0.6, expect_rebalance=True,
    )
    return {**report, "failures": failures}


SCENARIOS = {
    "flash_crowd": flash_crowd,
    "span_loss": span_loss,
    "diurnal": diurnal,
}


def run_scenario(
    name: str, sessions: int | None = None, seed: int | None = None
) -> dict:
    """Run one scenario under a fresh engine; returns its JSON report
    (metrics + per-server counters + gate failures + engine stats)."""
    from bloombee_tpu.sim.engine import SimEngine

    if sessions is None:
        sessions = int(env.get("BBTPU_SIM_SESSIONS"))
    if seed is None:
        seed = int(env.get("BBTPU_SIM_SEED"))
    engine = SimEngine()
    wall0 = clock.perf_counter()
    report = engine.run(SCENARIOS[name], sessions, seed)
    report["wall_s"] = round(clock.perf_counter() - wall0, 3)
    report["advances"] = engine.advances
    report["sessions_requested"] = sessions
    report["seed"] = seed
    return report
