"""Worker server: hosts a contiguous span of transformer blocks.

Replaces the reference's Server/ModuleContainer/TransformerConnectionHandler/
hivemind-Runtime stack (/root/reference/src/bloombee/server/server.py:97-911,
handler.py:373-3273) with one asyncio process per TPU host: RPC handlers feed
a single prioritized compute queue in front of the jitted span executor.
"""

from bloombee_tpu.server.block_server import BlockServer

__all__ = ["BlockServer"]
