"""Speculative generation over the swarm.

Port of the reference's DistributedLlamaForSpeculativeGeneration.generate
loop (/root/reference/src/bloombee/models/llama/speculative_model.py:33-117):
draft a tree rooted at the last certain token, verify the linearized tree in
ONE distributed step (tree mask + depth positions, KV written speculatively),
accept a path, and tell the servers which speculative slots survive (they
compact + commit on device). Greedy mode is token-exact with plain greedy
decode.

Round structure: every round's tree has node 0 = the bonus token from the
previous round (certain, always accepted) with the drafter's tree hanging
under it — so the certain token's KV is written in the same step as the
drafts, and the accept metadata rides the NEXT round's step (no extra RTT,
cf. the reference's set_kv_cache piggybacking).

Batch size 1 per session for now (the reference pads per-sample trees to a
common shape; that generalization is wiring, not design).
"""

from __future__ import annotations

import numpy as np

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.spec.drafter import GreedyTreeDrafter
from bloombee_tpu.spec.tree import DraftTree, tree_attention_mask
from bloombee_tpu.spec.verify import accept_greedy


async def generate_speculative(
    model: DistributedModelForCausalLM,
    drafter: GreedyTreeDrafter,
    input_ids: np.ndarray,  # [1, S]
    max_new_tokens: int,
    session=None,
) -> np.ndarray:
    input_ids = np.asarray(input_ids)
    assert input_ids.shape[0] == 1, "speculative path is per-sequence for now"
    b, s = input_ids.shape
    tree_size = 1 + sum(
        int(np.prod(drafter.branching[: i + 1]))
        for i in range(len(drafter.branching))
    )
    max_length = s + max_new_tokens + (tree_size + 1) * 2  # tree spike room
    own = session is None
    if own:
        session = model.inference_session(max_length, b)
        await session.__aenter__()
    try:
        ids = list(input_ids[0])
        # prefill -> logits at the last prompt token
        hidden = model.embed(np.asarray([ids]))
        out = await session.step(hidden)
        root_logits = model.logits(out[:, -1:])[0, 0]
        bonus = int(np.argmax(root_logits))
        new_tokens = [bonus]
        pending_accept = None

        while len(new_tokens) < max_new_tokens:
            # tree: node 0 = bonus (certain), drafter's tree under it
            sub, _probs = drafter.build(np.asarray(ids + new_tokens))
            tokens = np.concatenate([[new_tokens[-1]], sub.tokens])
            parents = np.concatenate(
                [[-1], np.where(sub.parents < 0, 0, sub.parents + 1)]
            ).astype(np.int32)
            tree = DraftTree(tokens=tokens, parents=parents)
            mask = tree_attention_mask(tree)[None]  # [1, T, T]
            depths = tree.depths()[None]  # [1, T]

            h_tree = model.embed(tree.tokens[None])
            out = await session.step(
                h_tree,
                commit=False,
                tree_mask=mask,
                depths=depths,
                accept=pending_accept,
            )
            logits = model.logits(out)[0]  # [T, V]

            accepted, nxt_bonus = accept_greedy(tree, root_logits, logits)
            # node 0 is certain and always accepted first
            assert accepted and accepted[0] == 0
            pending_accept = [np.asarray(accepted)]
            accepted_tokens = [int(tree.tokens[a]) for a in accepted[1:]]
            # accepted rows of h_tree ARE the history inputs — no re-embed
            session.record_history(np.asarray(h_tree[:, accepted]))
            root_logits = logits[accepted[-1]]
            new_tokens.extend(accepted_tokens)
            new_tokens.append(nxt_bonus)

        if pending_accept is not None:
            await session.send_accept(pending_accept)
        # every token except the final bonus is committed in server KV, so
        # only that may be trimmed — a resumed session must see ids that
        # match the committed cache (may overshoot max_new_tokens by up to
        # the accepted path length, like the reference's tree spikes)
        if len(new_tokens) > max_new_tokens:
            new_tokens = new_tokens[:-1]
        return np.asarray([ids + new_tokens])
    finally:
        if own:
            await session.__aexit__(None, None, None)
