"""Native (C++) runtime components, loaded via ctypes.

Compiled lazily on first use with the system toolchain and cached under
~/.cache/bloombee_tpu; every caller must tolerate `None` (pure-Python
fallback) so the framework works on toolchain-less hosts.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import pathlib
import subprocess

logger = logging.getLogger(__name__)

_SRC_DIR = pathlib.Path(__file__).parent
_CACHE = pathlib.Path.home() / ".cache" / "bloombee_tpu"

_byte_split_lib = None
_tried = False


def _build(src: pathlib.Path) -> pathlib.Path | None:
    code = src.read_bytes()
    tag = hashlib.sha1(code).hexdigest()[:12]
    out = _CACHE / f"{src.stem}-{tag}.so"
    if out.exists():
        return out
    _CACHE.mkdir(parents=True, exist_ok=True)
    # build to a process-unique temp path, then rename atomically so
    # concurrent processes never dlopen a half-written .so
    import os

    tmp = out.with_suffix(f".tmp-{os.getpid()}")
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", str(src), "-o", str(tmp)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, out)
        return out
    except Exception as e:
        logger.info("native build failed (%s); using numpy fallback", e)
        tmp.unlink(missing_ok=True)
        return None


_paged_lib = None
_paged_tried = False


def paged_table_lib():
    """ctypes handle to the native paged table, or None."""
    global _paged_lib, _paged_tried
    if _paged_tried:
        return _paged_lib
    _paged_tried = True
    so = _build(_SRC_DIR / "paged_table.cc")
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(str(so))
        i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)
        sigs = {
            "pt_create": ([i64, i64], i64),
            "pt_destroy": ([i64], None),
            "pt_free_pages": ([i64], i64),
            "pt_add_seq": ([i64, i64], i64),
            "pt_has_seq": ([i64, i64], i64),
            "pt_drop_seq": ([i64, i64], i64),
            "pt_l_acc": ([i64, i64], i64),
            "pt_l_seq": ([i64, i64], i64),
            "pt_num_seq_pages": ([i64, i64], i64),
            "pt_assign_write_slots": (
                [i64, i64, i64, ctypes.c_int32, i32p], i64,
            ),
            "pt_commit": ([i64, i64, i64], i64),
            "pt_accept": ([i64, i64, i64], i64),
            "pt_rollback": ([i64, i64], i64),
            "pt_truncate_speculative": ([i64, i64, i64], i64),
            "pt_reset_seq": ([i64, i64], i64),
            "pt_restore_committed": ([i64, i64, i64], i64),
            "pt_page_row": ([i64, i64, i32p, i64], i64),
            "pt_range_slots": ([i64, i64, i64, i64, i32p], i64),
        }
        for name, (args, res) in sigs.items():
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        _paged_lib = lib
    except Exception as e:  # pragma: no cover
        logger.info("native load failed (%s); using python table", e)
    return _paged_lib


def byte_split_lib():
    """ctypes handle to the byte-split codec, or None."""
    global _byte_split_lib, _tried
    if _tried:
        return _byte_split_lib
    _tried = True
    so = _build(_SRC_DIR / "byte_split.cc")
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(str(so))
        for fn in ("byte_split_2", "byte_merge_2"):
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ]
            getattr(lib, fn).restype = None
        _byte_split_lib = lib
    except Exception as e:  # pragma: no cover
        logger.info("native load failed (%s); using numpy fallback", e)
    return _byte_split_lib
