// Native paged KV table: the hot host-side control plane of every serving
// step (slot assignment, commit/rollback/accept, page bookkeeping).
// Semantics mirror bloombee_tpu/kv/paged.py EXACTLY — including the LIFO
// free-list order — so slot assignment is bit-identical to the Python
// table (the randomized equivalence test relies on that).
//
// C ABI, driven via ctypes. Error codes:
//   >= 0 success (payload-dependent meaning)
//   -1 unknown sequence        -2 out of pages
//   -3 invalid argument        -4 unknown table handle

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace {

struct Seq {
  std::vector<int32_t> pages;
  int64_t l_acc = 0;
  int64_t l_seq = 0;
};

struct Table {
  int64_t num_pages;
  int64_t page_size;
  std::vector<int32_t> free_list;  // LIFO: pop from the back
  std::unordered_map<int64_t, Seq> seqs;
};

// handles are the Table pointers themselves: no shared registry, so
// concurrent create/destroy from different threads cannot race a map
Table* get(int64_t h) { return reinterpret_cast<Table*>(h); }

int64_t pages_for(const Table& t, int64_t tokens) {
  return (tokens + t.page_size - 1) / t.page_size;
}

void trim(Table& t, Seq& s) {
  int64_t keep = pages_for(t, s.l_seq > s.l_acc ? s.l_seq : s.l_acc);
  while ((int64_t)s.pages.size() > keep) {
    t.free_list.push_back(s.pages.back());
    s.pages.pop_back();
  }
}

}  // namespace

extern "C" {

int64_t pt_create(int64_t num_pages, int64_t page_size) {
  if (num_pages <= 0 || page_size <= 0) return -3;
  Table* t = new Table;
  t->num_pages = num_pages;
  t->page_size = page_size;
  // python fills range(num_pages-1, -1, -1) and pops from the END, so the
  // first page handed out is page 0
  t->free_list.reserve(num_pages);
  for (int64_t p = num_pages - 1; p >= 0; --p)
    t->free_list.push_back((int32_t)p);
  return reinterpret_cast<int64_t>(t);
}

void pt_destroy(int64_t h) {
  delete reinterpret_cast<Table*>(h);
}

int64_t pt_free_pages(int64_t h) {
  Table* t = get(h);
  return t ? (int64_t)t->free_list.size() : -4;
}

int64_t pt_add_seq(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  if (t->seqs.count(sid)) return -3;
  t->seqs[sid] = Seq{};
  return 0;
}

int64_t pt_has_seq(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  return t->seqs.count(sid) ? 1 : 0;
}

int64_t pt_drop_seq(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  for (int32_t p : it->second.pages) t->free_list.push_back(p);
  t->seqs.erase(it);
  return 0;
}

int64_t pt_l_acc(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  return it == t->seqs.end() ? -1 : it->second.l_acc;
}

int64_t pt_l_seq(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  return it == t->seqs.end() ? -1 : it->second.l_seq;
}

int64_t pt_num_seq_pages(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  return it == t->seqs.end() ? -1 : (int64_t)it->second.pages.size();
}

// Assign flat slots for the next num_tokens tokens; writes them to out.
// Returns num_tokens, or an error code.
int64_t pt_assign_write_slots(int64_t h, int64_t sid, int64_t num_tokens,
                              int32_t commit, int32_t* out) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (num_tokens < 0) return -3;
  int64_t start = s.l_seq;
  // validation precedes capacity (and any mutation) — same order as the
  // Python table, so both raise the same error for the same op
  if (commit && s.l_acc != start) return -3;
  int64_t need = pages_for(*t, start + num_tokens) - (int64_t)s.pages.size();
  if (need > (int64_t)t->free_list.size()) return -2;
  for (int64_t i = 0; i < need; ++i) {
    s.pages.push_back(t->free_list.back());
    t->free_list.pop_back();
  }
  for (int64_t i = 0; i < num_tokens; ++i) {
    int64_t pos = start + i;
    out[i] = s.pages[pos / t->page_size] * (int32_t)t->page_size +
             (int32_t)(pos % t->page_size);
  }
  s.l_seq = start + num_tokens;
  if (commit) s.l_acc = s.l_seq;
  return num_tokens;
}

int64_t pt_commit(int64_t h, int64_t sid, int64_t length /* -1 = l_seq */) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (length < 0) length = s.l_seq;
  if (length < s.l_acc || length > s.l_seq) return -3;
  s.l_acc = length;
  s.l_seq = length;
  trim(*t, s);
  return 0;
}

int64_t pt_accept(int64_t h, int64_t sid, int64_t num_accepted) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (num_accepted < 0 || num_accepted > s.l_seq - s.l_acc) return -3;
  s.l_acc += num_accepted;
  s.l_seq = s.l_acc;
  trim(*t, s);
  return 0;
}

int64_t pt_rollback(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  s.l_seq = s.l_acc;
  trim(*t, s);
  return 0;
}

// Partial rollback: drop speculative tokens past `length`, keeping earlier
// still-speculative ones (a failed dispatch stacked atop uncommitted
// prefill chunks must undo only its own writes).
int64_t pt_truncate_speculative(int64_t h, int64_t sid, int64_t length) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (length < s.l_acc || length > s.l_seq) return -3;
  s.l_seq = length;
  trim(*t, s);
  return 0;
}

// Writes the page list (padded positions untouched); returns page count or
// error.
int64_t pt_page_row(int64_t h, int64_t sid, int32_t* out, int64_t max_pages) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if ((int64_t)s.pages.size() > max_pages) return -3;
  for (std::size_t i = 0; i < s.pages.size(); ++i) out[i] = s.pages[i];
  return (int64_t)s.pages.size();
}

// Flat slots for positions [start, end); returns count or error.
int64_t pt_range_slots(int64_t h, int64_t sid, int64_t start, int64_t end,
                       int32_t* out) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (start < 0 || end < start ||
      end > (int64_t)s.pages.size() * t->page_size)
    return -3;
  for (int64_t pos = start; pos < end; ++pos) {
    out[pos - start] = s.pages[pos / t->page_size] * (int32_t)t->page_size +
                       (int32_t)(pos % t->page_size);
  }
  return end - start;
}

int64_t pt_reset_seq(int64_t h, int64_t sid) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  s.l_acc = 0;
  s.l_seq = 0;
  trim(*t, s);
  return 0;
}

int64_t pt_restore_committed(int64_t h, int64_t sid, int64_t l_acc) {
  Table* t = get(h);
  if (!t) return -4;
  auto it = t->seqs.find(sid);
  if (it == t->seqs.end()) return -1;
  Seq& s = it->second;
  if (l_acc < 0 || l_acc > s.l_seq) return -3;
  s.l_acc = l_acc;
  return 0;
}

}  // extern "C"
