"""bbtpu-lint (bloombee_tpu/analysis): one true-positive and one
true-negative fixture per rule BB001-BB008, plus suppression and
baseline mechanics. Fixtures run through `analyze_source` on in-memory
sources, so these tests never depend on the live tree's findings."""

import textwrap

from bloombee_tpu.analysis import analyze_source
from bloombee_tpu.analysis.cli import main as cli_main
from bloombee_tpu.analysis.core import Finding, SourceFile

CLIENT = "bloombee_tpu/client/mod.py"
SERVER = "bloombee_tpu/server/mod.py"


def codes(src: str, path: str = CLIENT) -> list[str]:
    return [
        f.code
        for f in analyze_source({path: textwrap.dedent(src)})
    ]


# ------------------------------------------------------------------ BB001
BB001_TP = """
    def step(mgr, handle, h):
        mgr.write_slots_ragged(handle, [1], commit=False)
        return h
"""

BB001_TN = """
    def step(mgr, handle, h):
        try:
            mgr.write_slots_ragged(handle, [1], commit=False)
            mgr.commit(handle)
        except Exception:
            mgr.rollback(handle)
            raise
        return h
"""


def test_bb001_true_positive():
    assert codes(BB001_TP) == ["BB001"]


def test_bb001_true_negative():
    assert codes(BB001_TN) == []


def test_bb001_committed_write_is_quiet():
    # commit=True (and commit passed through a variable) is the
    # callee's contract, not a speculative site
    assert codes(
        """
        def f(mgr, handle):
            mgr.write_slots(handle, 2, commit=True)
            mgr.prefill(handle, commit=commit_flag)
        """
    ) == []


def test_bb001_finally_counts_as_recovery():
    assert codes(
        """
        def f(mgr, handle):
            try:
                mgr.assign_write_slots(handle, 4, commit=False)
            finally:
                mgr.truncate_speculative(handle, snaps)
        """
    ) == []


# ------------------------------------------------------------------ BB002
BB002_TP = """
    class C:
        def f(self, conn):
            with self._lock:
                return conn.recv()
"""

BB002_TN = """
    class C:
        def f(self, conn):
            with self._lock:
                self.n = 1
            return conn.recv()

        async def g(self, conn):
            async with self._alock:
                return await conn.recv()
"""


def test_bb002_true_positive():
    assert codes(BB002_TP) == ["BB002"]


def test_bb002_true_negative():
    # blocking outside the lock, or under an asyncio lock (which does
    # not pin a thread), is fine
    assert codes(BB002_TN) == []


def test_bb002_locked_decorator_and_nested_def():
    src = """
        class C:
            @_locked
            def f(self):
                return self.future.result()

            @_locked
            def g(self):
                def later():
                    return self.future.result()
                return later
    """
    # f() blocks under the decorator's lock; g() only DEFINES a
    # function, which does not run under the lock
    assert codes(src) == ["BB002"]


# ------------------------------------------------------------------ BB003
BB003_TP = """
    def f(self):
        with self.table._lock:
            with self.manager._lock:
                pass
"""

BB003_TN = """
    def f(self):
        with self.manager._lock:
            with self.table._lock:
                with self.compute.queue_lock:
                    pass
"""


def test_bb003_true_positive():
    assert codes(BB003_TP) == ["BB003"]


def test_bb003_true_negative():
    assert codes(BB003_TN) == []


# ----------------------------------------- transitive BB002/BB003 (v2)
def findings(src: str, path: str = CLIENT):
    return analyze_source({path: textwrap.dedent(src)})


BB002_TRANSITIVE_TP = """
    class C:
        def hot(self, conn):
            with self._lock:
                self.helper(conn)

        def helper(self, conn):
            return conn.recv()
"""


def test_bb002_transitive_chain_is_flagged_with_trace():
    """The lock holder is flagged even though the blocking call lives
    in a lock-free callee — with the full call chain in the finding."""
    fs = findings(BB002_TRANSITIVE_TP)
    assert [f.code for f in fs] == ["BB002"]
    assert fs[0].chain, "transitive finding carries no call chain"
    assert "helper" in " -> ".join(fs[0].chain)
    assert "recv" in fs[0].message


def test_bb002_transitive_quiet_when_chain_broken():
    # same shape, but the callee no longer blocks: no finding
    assert codes(
        """
        class C:
            def hot(self, conn):
                with self._lock:
                    self.helper(conn)

            def helper(self, conn):
                return conn.poll_nowait()
        """
    ) == []


def test_bb002_transitive_two_deep():
    fs = findings(
        """
        class C:
            def hot(self, conn):
                with self._lock:
                    self.mid(conn)

            def mid(self, conn):
                return self.leaf(conn)

            def leaf(self, conn):
                return conn.recv()
        """
    )
    assert [f.code for f in fs] == ["BB002"]
    chain = " -> ".join(fs[0].chain)
    assert "mid" in chain and "leaf" in chain


def test_bb002_transitive_survives_recursion_and_cycles():
    # recursion (f -> f) and a call cycle (a -> b -> a) must neither
    # hang the reachability pass nor suppress the real finding
    fs = findings(
        """
        class C:
            def hot(self, conn):
                with self._lock:
                    self.a(conn)

            def a(self, conn, n=0):
                if n:
                    return self.a(conn, n - 1)
                return self.b(conn)

            def b(self, conn):
                self.a(conn)
                return conn.recv()
        """
    )
    assert [f.code for f in fs] == ["BB002"]


def test_bb003_transitive_descending_through_call():
    """Holding the paged-table lock (70) while CALLING a helper that
    takes the cache-manager lock (60) is the same ABBA setup as nesting
    the `with` blocks directly."""
    fs = findings(
        """
        class C:
            def f(self):
                with self.table._lock:
                    self.grab_manager()

            def grab_manager(self):
                with self.manager._lock:
                    pass
        """
    )
    assert [f.code for f in fs] == ["BB003"]
    assert fs[0].chain


def test_bb003_transitive_ascending_is_quiet():
    assert codes(
        """
        class C:
            def f(self):
                with self.manager._lock:
                    self.grab_table()

            def grab_table(self):
                with self.table._lock:
                    pass
        """
    ) == []


# ------------------------------------------------------------------ BB009
BB009_TP = """
    import clock

    async def tick(self):
        clock.sleep(0.1)
        return 1
"""

BB009_TN = """
    import clock

    async def tick(self, entry):
        await clock.async_sleep(0.1)
        return await entry.resolve()

    def sync_path(self):
        clock.sleep(0.1)
"""


def test_bb009_true_positive():
    assert codes(BB009_TP) == ["BB009"]


def test_bb009_true_negative():
    # awaited calls suspend instead of blocking, and sync defs are
    # BB002's territory (they don't run on the loop by construction)
    assert codes(BB009_TN) == []


def test_bb009_serialization_under_async_lock():
    fs = findings(
        """
        class C:
            async def send(self, tensors):
                async with self._send_lock:
                    tm, blobs = serialize_tensors(tensors, "none")
                    return tm
        """
    )
    assert [f.code for f in fs] == ["BB009"]
    assert "critical section" in fs[0].message


def test_bb009_transitive_under_async_lock():
    """Under an asyncio lock the search goes through the call graph:
    the helper's sync blocking site convoys every task queued on the
    lock, even though the hot function never blocks directly."""
    fs = findings(
        """
        class C:
            async def send(self, tensors):
                async with self._send_lock:
                    return self.encode(tensors)

            def encode(self, tensors):
                return serialize_tensors(tensors, "none")
        """
    )
    assert [f.code for f in fs] == ["BB009"]
    assert fs[0].chain


def test_bb009_transitive_quiet_without_lock():
    # the transitive mode is deliberately lock-scoped: helper indirection
    # on the plain hot path would be too false-positive-prone
    assert codes(
        """
        class C:
            async def send(self, tensors):
                return self.encode(tensors)

            def encode(self, tensors):
                return serialize_tensors(tensors, "none")
        """
    ) == []


def test_bb009_noqa_suppresses():
    assert codes(
        """
        async def tick(self):
            clock.sleep(0.1)  # bbtpu: noqa[BB009]
        """
    ) == []


# ------------------------------------------------------------------ BB010
BB010_TP = """
    def kick(self, coro):
        asyncio.create_task(coro)
"""

BB010_TN = """
    def kick(self, coro, loop):
        t = asyncio.create_task(coro)
        self._tasks.add(t)
        asyncio.create_task(coro).add_done_callback(self._tasks.discard)
        return asyncio.ensure_future(coro, loop=loop)
"""


def test_bb010_true_positive():
    fs = findings(BB010_TP)
    assert [f.code for f in fs] == ["BB010"]
    assert "_spawn" in fs[0].message


def test_bb010_true_negative():
    assert codes(BB010_TN) == []


# ------------------------------------------------------------------ BB011
BB011_TP = """
    class BlockServer:
        def decode_group(self, out_dev):
            return self._finish(out_dev)

        def _finish(self, out_dev):
            return float(out_dev.sum())
"""

BB011_TN = """
    class BlockServer:
        def cold_path(self, out_dev):
            return out_dev.item()

        def decode_group(self, lens):
            return int(lens.max())
"""


def test_bb011_true_positive_transitive_chain():
    fs = findings(BB011_TP)
    assert [f.code for f in fs] == ["BB011"]
    assert "decode_group" in " -> ".join(fs[0].chain)
    assert "_finish" in " -> ".join(fs[0].chain)


def test_bb011_true_negative():
    # .item() off the hot path, and int() of a host-side length, are
    # both quiet
    assert codes(BB011_TN) == []


def test_bb011_direct_sync_in_hot_root():
    assert codes(
        """
        class BlockServer:
            def tree_group(self, members):
                out = self.executor.tree_group(members)
                out.block_until_ready()
                return out
        """
    ) == ["BB011"]


def test_bb011_offloaded_and_host_bound_are_quiet():
    # the one deliberate d2h runs via asyncio.to_thread (off the
    # compute queue), and names bound from to_thread/fetch are host
    # values — converting them again is not a sync
    assert codes(
        """
        class BlockServer:
            async def decode_group(self, out_dev):
                out = await asyncio.to_thread(self.executor.fetch, out_dev)
                arr = np.asarray(out, dtype=np.int32)
                toks = await asyncio.to_thread(
                    lambda: np.asarray(out_dev, dtype=np.int32)
                )
                return arr, toks
        """
    ) == []


def test_bb011_ndarray_annotated_param_is_quiet():
    # an np.ndarray-annotated parameter declares the value host-side:
    # the fetch already happened at the caller's chokepoint
    assert codes(
        """
        class BlockServer:
            def decode_group(self, out: np.ndarray):
                return np.asarray(out, dtype=np.float32)
        """
    ) == []


def test_bb011_noqa_suppresses():
    assert codes(
        """
        class BlockServer:
            def decode_group(self, out_dev):
                return np.asarray(out_dev)  # bbtpu: noqa[BB011] wire-bound
        """
    ) == []


# ------------------------------------------------------------------ BB012
RUNTIME = "bloombee_tpu/runtime/mod.py"


def jit_src(body: str) -> str:
    """Prelude (a runtime-style jit entry) + a test body, each dedented
    on its own so their indent levels need not match."""
    return textwrap.dedent(BB012_PRELUDE) + textwrap.dedent(body)


BB012_PRELUDE = """
    import functools
    import jax

    def span_step_impl(params, ak, av, h, *, b, t):
        return h, ak, av

    span_step = functools.partial(
        jax.jit, static_argnames=("b", "t"),
        donate_argnames=("ak", "av"),
    )(span_step_impl)
"""

BB012_TP = BB012_PRELUDE + """
    class Exec:
        def step(self, params, arena, hidden):
            t = hidden.shape[1]
            h, ak, av = span_step(
                params, arena["k"], arena["v"], hidden, b=2, t=t
            )
            return h, ak, av
"""

BB012_TN = BB012_PRELUDE + """
    class Exec:
        def step(self, params, arena, hidden):
            t = next_pow2(hidden.shape[1])
            h, ak, av = span_step(
                params, arena["k"], arena["v"], hidden, b=2, t=t
            )
            return h, ak, av
"""


def test_bb012_true_positive_raw_shape():
    fs = findings(BB012_TP, path=RUNTIME)
    assert [f.code for f in fs] == ["BB012"]
    assert "t=t" in fs[0].message


def test_bb012_true_negative_bucketed():
    # the bucketer anywhere on the derivation path clears the value
    assert codes(BB012_TN, path=RUNTIME) == []


def test_bb012_constant_static_is_quiet():
    assert codes(
        jit_src("""
        class Exec:
            def step(self, params, arena, hidden):
                h, ak, av = span_step(
                    params, arena["k"], arena["v"], hidden, b=2, t=8
                )
                return h, ak, av
        """),
        path=RUNTIME,
    ) == []


def test_bb012_transitive_derivation_is_flagged():
    # t -> t_raw -> len(rows): two assignment hops, still raw
    assert codes(
        jit_src("""
        class Exec:
            def step(self, params, arena, hidden, rows):
                t_raw = len(rows)
                t = t_raw + 1
                h, ak, av = span_step(
                    params, arena["k"], arena["v"], hidden, b=2, t=t
                )
                return h, ak, av
        """),
        path=RUNTIME,
    ) == ["BB012"]


def test_bb012_entries_outside_runtime_are_out_of_scope():
    # client-side jit helpers are not serving hot paths
    assert codes(BB012_TP, path=CLIENT) == []


# ------------------------------------------------------------------ BB013
BB013_TP = BB012_PRELUDE + """
    class Exec:
        def step(self, params, arena, hidden):
            h, ak, av = span_step(
                params, arena["k"], arena["v"], hidden, b=2, t=8
            )
            leak = arena["k"].sum()
            return h, leak
"""

BB013_TN = BB012_PRELUDE + """
    class Exec:
        def step(self, params, arena, hidden):
            ak, av = arena["k"], arena["v"]
            h, ak, av = span_step(params, ak, av, hidden, b=2, t=8)
            return h, ak, av
"""


def test_bb013_true_positive():
    fs = findings(BB013_TP, path=RUNTIME)
    assert [f.code for f in fs] == ["BB013"]
    assert "DONATED" in fs[0].message
    assert "arena['k']" in fs[0].message


def test_bb013_true_negative_rebound():
    # rebinding to the returned arrays (same statement) is THE correct
    # donation pattern
    assert codes(BB013_TN, path=RUNTIME) == []


def test_bb013_later_rebind_kills_tracking():
    assert codes(
        jit_src("""
        class Exec:
            def step(self, params, arena, hidden):
                h, ak, av = span_step(
                    params, arena["k"], arena["v"], hidden, b=2, t=8
                )
                arena["k"], arena["v"] = ak, av
                return h, arena["k"].sum()
        """),
        path=RUNTIME,
    ) == []


def test_bb013_except_handler_read_is_quiet():
    # the donated-arena self-heal contract probes consumed buffers in
    # the except handler on purpose (_arena_consumed)
    assert codes(
        jit_src("""
        class Exec:
            def step(self, params, arena, hidden):
                try:
                    h, ak, av = span_step(
                        params, arena["k"], arena["v"], hidden, b=2, t=8
                    )
                except Exception:
                    if self._arena_consumed(arena["k"]):
                        self._rebuild_after_failure("step")
                    raise
                return h, ak, av
        """),
        path=RUNTIME,
    ) == []


def test_bb013_sibling_branch_read_is_quiet():
    # mutually exclusive if/else arms never execute in sequence
    assert codes(
        jit_src("""
        class Exec:
            def step(self, params, arena, hidden, fancy):
                if fancy:
                    h, ak, av = span_step(
                        params, arena["k"], arena["v"], hidden, b=2, t=8
                    )
                else:
                    h = hidden
                    ak, av = arena["k"], arena["v"]
                return h, ak, av
        """),
        path=RUNTIME,
    ) == []


def test_bb013_decorated_jit_form_and_noqa():
    src = jit_src("""
    @functools.partial(jax.jit, donate_argnames=("ak",))
    def write_all(ak, xs):
        return ak

    class Exec:
        def flush(self, arena, xs):
            ak = write_all(arena["k"], xs)
            return arena["k"].shape{noqa}
    """)
    assert codes(src.format(noqa=""), path=RUNTIME) == ["BB013"]
    assert codes(
        src.format(noqa="  # bbtpu: noqa[BB013] probe only"),
        path=RUNTIME,
    ) == []


# ------------------------------------------------------------------ BB004
BB004_TP = """
    import dataclasses

    @dataclasses.dataclass
    class Info:
        version: str

        @classmethod
        def from_wire(cls, d):
            return cls(**d)
"""

BB004_TN = """
    import dataclasses

    @dataclasses.dataclass
    class Info:
        version: str = "v0"

        @classmethod
        def from_wire(cls, d):
            known = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in d.items() if k in known})
"""


def test_bb004_true_positive():
    # both defects fire: the unfiltered splat (newer peer's unknown
    # field) and the undefaulted field (older peer's missing field)
    found = codes(BB004_TP, path="bloombee_tpu/swarm/mod.py")
    assert found == ["BB004", "BB004"]


def test_bb004_true_negative():
    assert codes(BB004_TN, path="bloombee_tpu/swarm/mod.py") == []


def test_bb004_explicit_construction_opts_out():
    # field-by-field from_wire (TensorMeta-style) handles versioning
    # manually; the splat rules don't apply
    assert codes(
        """
        import dataclasses

        @dataclasses.dataclass
        class Meta:
            dtype: str

            @classmethod
            def from_wire(cls, d):
                return cls(d["dtype"])
        """,
        path="bloombee_tpu/wire/mod.py",
    ) == []


# ------------------------------------------------------------------ BB005
BB005_TP = """
    import os
    TIMEOUT = float(os.environ.get("BBTPU_TIMEOUT_S", "1"))
"""

BB005_TN = """
    import os
    from bloombee_tpu.utils import env
    TIMEOUT = env.get("BBTPU_TIMEOUT_S")
    HOME = os.environ.get("HOME")
    os.environ["BBTPU_TIMEOUT_S"] = "2"
"""


def test_bb005_true_positive():
    assert codes(BB005_TP) == ["BB005"]
    assert codes("import os\nX = os.getenv('BBTPU_X')\n") == ["BB005"]
    assert codes("import os\nX = os.environ['BBTPU_X']\n") == ["BB005"]


def test_bb005_true_negative():
    # registry reads, non-BBTPU keys, and writes (save/set/restore) are
    # all out of scope
    assert codes(BB005_TN) == []


# ------------------------------------------------------------------ BB006
BB006_TP = """
    class S:
        def step(self):
            self.widgets_made += 1
"""

BB006_TN = """
    class S:
        def step(self):
            self.widgets_made += 1
            self._scratch += 1

        def stats(self):
            return {"widgets_made": self.widgets_made}
"""


def test_bb006_true_positive():
    assert codes(BB006_TP, path=SERVER) == ["BB006"]


def test_bb006_true_negative():
    # surfaced via a stats() string key; underscore-prefixed private
    # bookkeeping never needs surfacing
    assert codes(BB006_TN, path=SERVER) == []


def test_bb006_surfacing_may_live_in_another_file():
    findings = analyze_source(
        {
            SERVER: textwrap.dedent(BB006_TP),
            "bloombee_tpu/cli/health.py": "KEYS = ('widgets_made',)\n",
        }
    )
    assert findings == []


def test_bb006_ignores_non_server_code():
    assert codes(BB006_TP, path=CLIENT) == []


# ------------------------------------------------------------------ BB007
BB007_TP = """
    import numpy as np

    def audit(primary_out, audited_hidden):
        if np.array_equal(primary_out, audited_hidden):
            return True
        return audited_hidden == primary_out
"""

BB007_TN = """
    import numpy as np

    def audit(primary_out, audited_hidden, expected_digest, tokens):
        ok = tensors_close(primary_out, audited_hidden)
        same_geom = primary_out.shape == audited_hidden.shape
        byte_check = out_digest(primary_out) == expected_digest
        toks = tokens == [1, 2, 3]
        return ok and same_geom and byte_check and toks
"""


def test_bb007_true_positive():
    # both the helper-call form and the bare `==` on two hidden-state
    # expressions are exact compares that convict honest ulp drift
    assert codes(BB007_TP, path=CLIENT) == ["BB007", "BB007"]


def test_bb007_true_negative():
    # tolerance compare, shape compare, byte-digest compare over the SAME
    # serialized array, and token-id compare are all legitimate
    assert codes(BB007_TN, path=CLIENT) == []


def test_bb007_scoped_to_client_server_paths():
    # test helpers asserting exactness on purpose live outside the
    # verification paths and stay quiet
    assert codes(BB007_TP, path="bloombee_tpu/kv/mod.py") == []


# ------------------------------------------------------------------ BB008
BB008_TP = """
    import time
    import time as _time

    def reap(sessions, lease_s):
        cutoff = time.monotonic() - lease_s
        time.sleep(0.1)
        return [s for s in sessions if s.t < cutoff], _time.time()
"""

BB008_TN = """
    import time
    from bloombee_tpu.utils import clock

    def measure(sessions, lease_s):
        t0 = time.perf_counter()
        cutoff = clock.monotonic() - lease_s
        clock.sleep(0.1)
        live = [s for s in sessions if s.t >= cutoff]
        return live, time.perf_counter() - t0
"""

BB008_FROM_IMPORT = """
    from time import monotonic

    def stamp():
        return monotonic()
"""


def test_bb008_true_positive():
    # every banned call fires, through the bare alias AND the `as _time`
    # alias — the rename idiom must not dodge the rule
    assert codes(BB008_TP, path=SERVER) == ["BB008", "BB008", "BB008"]


def test_bb008_true_negative():
    # clock.* calls and perf_counter duration measurement are the
    # sanctioned idioms; neither fires
    assert codes(BB008_TN, path=SERVER) == []


def test_bb008_flags_from_import():
    # `from time import monotonic` escapes the virtual clock as a bare
    # callable; the import itself is the finding (the call site no longer
    # mentions `time` at all)
    assert codes(BB008_FROM_IMPORT, path=SERVER) == ["BB008"]


def test_bb008_exempts_clock_module_and_harness_code():
    # utils/clock.py IS the real-time boundary; bench.py is an
    # out-of-package harness that reports wall time on purpose
    assert codes(BB008_TP, path="bloombee_tpu/utils/clock.py") == []
    assert codes(BB008_TP, path="bench.py") == []


# ------------------------------------------------- suppressions & baseline
def test_noqa_suppresses_named_code():
    src = 'import os\nX = os.getenv("BBTPU_X")  # bbtpu: noqa[BB005]\n'
    assert codes(src) == []


def test_noqa_bare_suppresses_everything():
    src = 'import os\nX = os.getenv("BBTPU_X")  # bbtpu: noqa\n'
    assert codes(src) == []


def test_noqa_wrong_code_does_not_suppress():
    src = 'import os\nX = os.getenv("BBTPU_X")  # bbtpu: noqa[BB001]\n'
    assert codes(src) == ["BB005"]


def test_noqa_applies_to_multiline_statement():
    src = (
        "def f(mgr, handle):\n"
        "    mgr.write_slots_ragged(  # bbtpu: noqa[BB001]\n"
        "        handle, [1], commit=False\n"
        "    )\n"
    )
    assert codes(src) == []


def test_fingerprint_survives_line_drift():
    src = 'import os\nX = os.getenv("BBTPU_X")\n'
    (f1,) = analyze_source({CLIENT: src})
    (f2,) = analyze_source({CLIENT: "# a new leading comment\n" + src})
    assert f1.line != f2.line
    assert f1.fingerprint() == f2.fingerprint()


def test_fingerprint_changes_when_line_changes():
    a = Finding("BB005", CLIENT, 2, "m", snippet="X = 1")
    b = Finding("BB005", CLIENT, 2, "m", snippet="X = 2")
    assert a.fingerprint() != b.fingerprint()


def test_source_file_rejects_unparsable_noqa_scan():
    sf = SourceFile(CLIENT, "x = 1  # bbtpu: noqa[BB001, BB005]\n")
    assert sf.noqa[1] == {"BB001", "BB005"}


def test_cli_baseline_workflow(tmp_path, monkeypatch, capsys):
    """new finding fails -> --update-baseline accepts it -> gate green
    -> the NEXT new finding fails again; --no-baseline sees through."""
    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "mod.py"
    mod.write_text('import os\nX = os.getenv("BBTPU_X")\n')
    argv = ["mod.py", "--baseline", "bl.txt"]

    assert cli_main(argv) == 1
    assert cli_main(argv + ["--update-baseline"]) == 0
    assert (tmp_path / "bl.txt").exists()
    assert cli_main(argv) == 0  # baselined finding no longer fails

    mod.write_text(
        'import os\nX = os.getenv("BBTPU_X")\n'
        'Y = os.getenv("BBTPU_Y")\n'
    )
    assert cli_main(argv) == 1  # only the NEW finding trips the gate
    out = capsys.readouterr()
    assert "BBTPU_Y" in out.out
    assert cli_main(argv + ["--no-baseline"]) == 1


def test_cli_json_output(tmp_path, monkeypatch, capsys):
    """--json emits the findings machine-readably on stdout (rule id,
    fingerprint, path:line, call chain) with the summary on stderr; the
    human text format is a separate code path and stays byte-stable."""
    import json

    monkeypatch.chdir(tmp_path)
    mod = tmp_path / "mod.py"
    mod.write_text(
        "class C:\n"
        "    def hot(self, conn):\n"
        "        with self._lock:\n"
        "            self.helper(conn)\n"
        "    def helper(self, conn):\n"
        "        return conn.recv()\n"
    )
    argv = ["mod.py", "--baseline", "bl.txt"]

    assert cli_main(argv + ["--json"]) == 1
    out = capsys.readouterr()
    doc = json.loads(out.out)  # stdout is pure JSON
    assert "bbtpu-lint" in out.err
    (f,) = doc["findings"]
    assert f["rule"] == "BB002"
    assert f["location"] == f"{f['path']}:{f['line']}"
    assert len(f["fingerprint"]) == 12
    assert any("helper" in hop for hop in f["chain"])

    # clean tree: stdout still pure JSON, empty findings, exit 0
    mod.write_text("x = 1\n")
    assert cli_main(argv + ["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == []


def test_cli_fingerprints_are_cwd_independent(tmp_path, monkeypatch,
                                              capsys):
    """A baseline written from the checkout root must still match when
    the CLI runs from an unrelated cwd with absolute path arguments
    (findings relativize against the detected checkout, not cwd)."""
    proj = tmp_path / "proj"
    (proj / "bloombee_tpu").mkdir(parents=True)
    (proj / "bloombee_tpu" / "__init__.py").write_text("")
    mod = proj / "bloombee_tpu" / "mod.py"
    mod.write_text('import os\nX = os.getenv("BBTPU_X")\n')
    bl = proj / "bl.txt"

    monkeypatch.chdir(proj)
    argv = ["bloombee_tpu", "--baseline", str(bl)]
    assert cli_main(argv + ["--update-baseline"]) == 0
    assert cli_main(argv) == 0

    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    monkeypatch.chdir(elsewhere)
    assert cli_main(
        [str(proj / "bloombee_tpu"), "--baseline", str(bl)]
    ) == 0
    capsys.readouterr()


def test_cli_select(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "mod.py").write_text(
        'import os\nX = os.getenv("BBTPU_X")\n'
    )
    base = ["mod.py", "--baseline", "bl.txt", "--no-baseline"]
    assert cli_main(base + ["--select", "BB001"]) == 0
    assert cli_main(base + ["--select", "BB005"]) == 1
    capsys.readouterr()
