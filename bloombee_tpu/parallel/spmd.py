"""Megatron-style SPMD block compute under shard_map (tp + sp + dp).

Replaces the reference's intra-host tensor parallelism
(/root/reference/src/bloombee/server/flexgen_tensor_parallel.py:172-828:
per-device CUDA streams, row/col weight slices, stream all-reduce) with the
TPU idiom: weights sharded over the "tp" mesh axis, local matmuls on each
shard, one psum over ICI after o_proj and down_proj. Attention runs as ring
attention over the "sp" axis, so long sequences scale across the mesh instead
of offloading to host.

All functions here execute INSIDE shard_map (they use axis primitives);
`shard_span_params` prepares the NamedSharding placement that makes shard_map
hand each device its local shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import rms_norm, silu_mlp
from bloombee_tpu.ops.rotary import apply_rotary, rotary_cos_sin
from bloombee_tpu.parallel.ring_attention import ring_attention

# PartitionSpecs for stacked span params [L, ...]; layer dim shards over pp
PARAM_SPECS = {
    "input_layernorm": P("pp", None),
    "post_attention_layernorm": P("pp", None),
    "q_proj": P("pp", None, "tp"),
    "k_proj": P("pp", None, "tp"),
    "v_proj": P("pp", None, "tp"),
    "o_proj": P("pp", "tp", None),
    "gate_proj": P("pp", None, "tp"),
    "up_proj": P("pp", None, "tp"),
    "down_proj": P("pp", "tp", None),
    "q_norm": P("pp", None),
    "k_norm": P("pp", None),
    # MoE (mixtral): experts shard over the tp axis = expert parallelism,
    # which the reference lacks entirely (SURVEY.md section 2.8)
    "router": P("pp", None, None),
    "experts_gate": P("pp", "tp", None, None),
    "experts_up": P("pp", "tp", None, None),
    "experts_down": P("pp", "tp", None, None),
}


def param_specs(params: dict) -> dict:
    return {k: PARAM_SPECS[k] for k in params}


def shard_span_params(params: dict, mesh: Mesh) -> dict:
    """Place stacked span params on the mesh (pp over layers, tp over
    heads/ffn)."""
    return {
        k: jax.device_put(v, NamedSharding(mesh, PARAM_SPECS[k]))
        for k, v in params.items()
    }


def spmd_block_forward(
    params_l: dict,  # one layer's LOCAL param shards
    hidden: jax.Array,  # [b_local, C, D] (dp-sharded batch, sp-sharded seq)
    *,
    spec: ModelSpec,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
) -> jax.Array:
    b, c, d = hidden.shape
    if spec.layer_types and "sliding" in spec.layer_types:
        raise NotImplementedError(
            "ring attention in the spmd path is full-causal; sliding-window "
            "families (mistral/gemma) aren't supported here yet"
        )
    if (
        spec.norm_type != "rms"
        or spec.alibi
        or spec.parallel_attn
        or spec.sandwich_norms
        or spec.mlp_type != "silu"
    ):
        # this body implements the llama/qwen3/mixtral shape only; biased
        # or structurally different families must fail loudly, not run with
        # silently dropped terms
        raise NotImplementedError(
            f"spmd block body doesn't cover family {spec.family!r} "
            "(ln/alibi/parallel-attn/sandwich/gelu variants)"
        )
    if any(k.endswith("_bias") for k in params_l):
        raise NotImplementedError(
            "spmd block body is bias-free; biased families (qwen2/bloom) "
            "aren't supported here yet"
        )
    tp = lax.axis_size(tp_axis)
    if spec.num_attention_heads % tp or spec.num_key_value_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_attention_heads="
            f"{spec.num_attention_heads} and num_key_value_heads="
            f"{spec.num_key_value_heads} (KV-head replication not yet "
            "implemented)"
        )
    h_local = spec.num_attention_heads // tp
    kv_local = spec.num_key_value_heads // tp
    hd = spec.head_dim

    sp_rank = lax.axis_index(sp_axis)
    positions = sp_rank * c + jnp.arange(c)
    positions = jnp.broadcast_to(positions[None], (b, c))
    cos, sin = rotary_cos_sin(positions, hd, spec.rope_theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)

    x = rms_norm(hidden, params_l["input_layernorm"], spec.rms_norm_eps)
    q = (x @ params_l["q_proj"]).reshape(b, c, h_local, hd)
    k = (x @ params_l["k_proj"]).reshape(b, c, kv_local, hd)
    v = (x @ params_l["v_proj"]).reshape(b, c, kv_local, hd)
    if spec.qk_norm:
        q = rms_norm(q, params_l["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params_l["k_norm"], spec.rms_norm_eps)
    q, k = apply_rotary(q, k, cos, sin)

    attn = ring_attention(q, k, v, axis_name=sp_axis, causal=True)
    partial = attn.reshape(b, c, h_local * hd) @ params_l["o_proj"]
    hidden = hidden + lax.psum(partial, tp_axis)

    x = rms_norm(hidden, params_l["post_attention_layernorm"], spec.rms_norm_eps)
    if spec.num_experts:
        # expert parallelism: full router everywhere, local expert shard
        # computes its weighted contribution, psum combines
        from bloombee_tpu.ops.moe import moe_mlp, router_topk_weights

        weights = router_topk_weights(
            x @ params_l["router"], spec.num_experts_per_tok,
            pre_softmax=spec.moe_pre_softmax, norm_topk=spec.moe_norm_topk,
        )  # [b, c, E] full
        e_local = params_l["experts_gate"].shape[0]
        rank = lax.axis_index(tp_axis)
        local_w = lax.dynamic_slice_in_dim(
            weights, rank * e_local, e_local, axis=-1
        )
        partial = moe_mlp(
            x, None, params_l["experts_gate"], params_l["experts_up"],
            params_l["experts_down"], spec.num_experts_per_tok,
            router_weights=local_w,
        )
    else:
        partial = silu_mlp(
            x, params_l["gate_proj"], params_l["up_proj"], params_l["down_proj"]
        )
    hidden = hidden + lax.psum(partial, tp_axis)
    return hidden


def spmd_span_forward(
    stacked_local: dict,  # local param shards with leading local-layer dim
    hidden: jax.Array,
    *,
    spec: ModelSpec,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
) -> jax.Array:
    def body(h, params_l):
        return (
            spmd_block_forward(
                params_l, h, spec=spec, sp_axis=sp_axis, tp_axis=tp_axis
            ),
            None,
        )

    hidden, _ = lax.scan(body, hidden, stacked_local)
    return hidden
