"""Off-loop wire codec pipeline (per-connection, bounded, ordered).

Tensor (de)serialization used to run synchronously inside wire/rpc.py
coroutines — the event loop stalled for every codec call. This module
moves that work into a small shared thread pool while keeping the two
invariants the RPC layer depends on:

- ordering: frames for one stream must not reorder. The receive side
  submits decode jobs as frames arrive but a single drain task awaits
  them in arrival order (wire/rpc.py), so concurrency never reorders a
  stream. The send side keeps order because stream senders await each
  frame before the next.
- backpressure: both directions are bounded per connection. TX holds a
  FlowLimiter slot (wire/flow.py AIMD) around encode+write, so a slow
  peer shrinks only its own connection's concurrency instead of
  convoying the loop; RX queues at most BBTPU_WIRE_PIPELINE_DEPTH frames
  — a full queue stops the socket reads and TCP pushes back on the peer.

BBTPU_WIRE_PIPELINE=0 restores the seed's fully synchronous scheduling
(frames stay byte-identical either way; the switch changes only where
codec work runs).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading

from bloombee_tpu.utils import env
from bloombee_tpu.wire import tensor_codec
from bloombee_tpu.wire.flow import FlowLimiter

env.declare(
    "BBTPU_WIRE_PIPELINE", bool, True,
    "run wire tensor (de)serialization off the event loop in the shared "
    "codec pool, bounded and ordered per connection; 0 restores the "
    "seed's synchronous codec scheduling (frames are byte-identical "
    "either way)",
)
env.declare(
    "BBTPU_WIRE_PIPELINE_DEPTH", int, 8,
    "per-connection bound on in-flight codec jobs: max queued inbound "
    "frames awaiting decode (past it the socket read stalls — TCP "
    "backpressure) and the FlowLimiter ceiling for concurrent sends",
)
env.declare(
    "BBTPU_WIRE_CODEC_THREADS", int, 2,
    "worker threads in the process-wide wire codec pool",
)
env.declare(
    "BBTPU_WIRE_PIPELINE_INLINE", int, 4096,
    "payloads smaller than this many bytes are (de)serialized in-line "
    "even when the pipeline is on — a thread hop costs more than codec "
    "work on tiny frames; 0 forces every frame through the pool",
)

_EXEC: concurrent.futures.ThreadPoolExecutor | None = None
_EXEC_GUARD = threading.Lock()


def codec_executor() -> concurrent.futures.ThreadPoolExecutor:
    """Process-wide codec pool, created on first use (thread count is
    pinned at creation; BBTPU_WIRE_CODEC_THREADS is read once)."""
    global _EXEC
    if _EXEC is None:
        with _EXEC_GUARD:
            if _EXEC is None:
                _EXEC = concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(1, env.get("BBTPU_WIRE_CODEC_THREADS")),
                    thread_name_prefix="bbtpu-codec",
                )
    return _EXEC


def encode_now(tensors, compression: bool = True, allowed=None):
    """Synchronous serialize (worker-thread body / legacy sync path)."""
    return tensor_codec.serialize_tensors(tensors, compression,
                                          allowed=allowed)


def decode_now(metas, blobs, writable: bool = False):
    """Synchronous deserialize (worker-thread body / legacy sync path)."""
    return tensor_codec.deserialize_tensors(metas, blobs, writable=writable)


class _NullSlot:
    """No-op stand-in for a FlowLimiter slot when the pipeline is off."""

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        return False


class CodecPipeline:
    """Per-connection codec scheduling state + counters.

    One instance per wire/rpc.py Connection. When disabled (env switch or
    legacy peer emulation) every entry point degrades to the synchronous
    in-line codec call the seed shipped."""

    def __init__(self, name: str = ""):
        self.enabled = bool(env.get("BBTPU_WIRE_PIPELINE"))
        self.depth = max(1, int(env.get("BBTPU_WIRE_PIPELINE_DEPTH")))
        self.inline_bytes = max(0, int(env.get("BBTPU_WIRE_PIPELINE_INLINE")))
        self.tx_flow = FlowLimiter(
            name=f"wire.tx:{name}" if name else "wire.tx",
            initial=2, lo=1, hi=self.depth,
        )
        self.tx_jobs = 0
        self.rx_jobs = 0
        self.rx_depth_max = 0
        self.rx_backpressure_waits = 0

    # ------------------------------------------------------------------ TX
    def tx_slot(self):
        """Bounded-send context: `async with pipeline.tx_slot(): ...`."""
        return self.tx_flow.slot() if self.enabled else _NullSlot()

    async def encode(self, tensors, compression: bool = True,
                     allowed=None):
        """Serialize a frame's tensors, off-loop when enabled and the
        payload is big enough for the thread hop to pay for itself."""
        self.tx_jobs += 1
        if (
            not self.enabled
            or not tensors
            or sum(int(getattr(t, "nbytes", 0)) for t in tensors)
            < self.inline_bytes
        ):
            return encode_now(tensors, compression, allowed)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            codec_executor(), encode_now, tensors, compression, allowed
        )

    # ------------------------------------------------------------------ RX
    def decode_submit(self, metas, blobs):
        """Submit one inbound frame's decode; returns the awaitable the
        connection's ordered drain task resolves. Payloads under the
        inline threshold decode here (already-resolved future) — the
        ordered FIFO still serializes dispatch either way. Only valid
        while the pipeline is enabled."""
        self.rx_jobs += 1
        loop = asyncio.get_running_loop()
        if sum(len(b) for b in blobs) < self.inline_bytes:
            fut = loop.create_future()
            try:
                fut.set_result(decode_now(metas, blobs))
            except Exception as e:  # noqa: BLE001 — drain maps to the frame
                fut.set_exception(e)
            return fut
        return loop.run_in_executor(codec_executor(), decode_now, metas,
                                    blobs)

    async def decode_wait(self, metas, blobs):
        """Decode an inbound payload for an unordered handler (unary/push):
        off-loop when enabled and big enough, in-line otherwise."""
        self.rx_jobs += 1
        if (
            not self.enabled
            or not blobs
            or sum(len(b) for b in blobs) < self.inline_bytes
        ):
            return decode_now(metas, blobs)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(codec_executor(), decode_now,
                                          metas, blobs)

    def note_rx_depth(self, depth: int) -> None:
        if depth > self.rx_depth_max:
            self.rx_depth_max = depth

    # ------------------------------------------------------------- counters
    def stats(self) -> dict:
        out = {
            "enabled": self.enabled,
            "depth": self.depth,
            "tx_jobs": self.tx_jobs,
            "rx_jobs": self.rx_jobs,
            "rx_depth_max": self.rx_depth_max,
            "rx_backpressure_waits": self.rx_backpressure_waits,
        }
        out.update({f"tx_{k}": v for k, v in self.tx_flow.stats().items()})
        return out
