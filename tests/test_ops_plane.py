"""Operations plane: ClientConfig, registry persistence, activation dumper,
warmup, env-flag table."""

import asyncio
import json
import os

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_ops")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), config


def _server(model_dir, reg_port, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=0, end=2, model_dir=model_dir,
        registry=RegistryClient("127.0.0.1", reg_port), **kw,
    )


def test_client_config_blocked_servers(tiny):
    """ClientConfig.blocked_servers removes a peer from routing (reference
    config.py allowed/blocked servers)."""
    model_dir, config = tiny

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, reg.port, throughput=10.0)
        s2 = _server(model_dir, reg.port, throughput=1.0)
        await s1.start()
        await s2.start()

        blocked = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
            config=ClientConfig(blocked_servers=[s1.server_id]),
        )
        sess = blocked.inference_session(8, 1)
        await sess.__aenter__()
        used = {s.peer_id for s in (x.span for x in sess._spans)}
        await sess.__aexit__(None, None, None)
        assert used == {s2.server_id}  # best peer blocked -> other chosen

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_registry_persistence_roundtrip(tmp_path):
    """A restarted registry reloads live records from its disk snapshot."""
    from bloombee_tpu.swarm.data import ServerInfo

    path = str(tmp_path / "registry.json")

    async def run():
        reg = RegistryServer(host="127.0.0.1", persist_path=path)
        await reg.start()
        client = RegistryClient("127.0.0.1", reg.port)
        info = ServerInfo(host="1.2.3.4", port=9, start_block=0, end_block=2)
        await client.declare_blocks(
            "m", "srv-a", range(0, 2), info, expiration=60.0
        )
        await client.close()
        await reg.stop()  # writes the final snapshot
        assert os.path.exists(path)

        reg2 = RegistryServer(host="127.0.0.1", persist_path=path)
        await reg2.start()
        client2 = RegistryClient("127.0.0.1", reg2.port)
        infos = await client2.get_module_infos("m", range(0, 2))
        assert all("srv-a" in mi.servers for mi in infos)
        assert infos[0].servers["srv-a"].host == "1.2.3.4"
        await client2.close()
        await reg2.stop()

    asyncio.run(run())


def test_activation_dumper(tiny, tmp_path, monkeypatch):
    model_dir, config = tiny
    dump_dir = str(tmp_path / "acts")
    monkeypatch.setenv("BBTPU_DUMP_ACTIVATIONS", dump_dir)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(model_dir, reg.port)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        ids = np.arange(5)[None, :] % config.vocab_size
        await model.generate(ids, max_new_tokens=3)
        await s.stop()
        await reg.stop()

    asyncio.run(run())
    files = sorted(os.listdir(dump_dir))
    assert len(files) >= 3  # prefill + decode steps
    d = np.load(os.path.join(dump_dir, files[0]))
    assert {"hidden_in", "hidden_out", "start_block", "end_block"} <= set(d)


def test_warmup_compiles_buckets(tiny):
    model_dir, config = tiny

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(model_dir, reg.port)
        await s.start()
        await s.warmup(batch_sizes=(1,), prefill_tokens=8)
        # cache must be fully released after warmup
        assert s.manager.tokens_left == s.manager.capacity_tokens
        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_env_describe_lists_declared_flags():
    from bloombee_tpu.utils import env

    table = env.describe()
    for name in ("BBTPU_MICROBATCH", "BBTPU_KV_QUANT",
                 "BBTPU_FLASH_ATTENTION", "BBTPU_DUMP_ACTIVATIONS",
                 "BBTPU_MIN_COMPRESS_BYTES"):
        assert name in table


def test_hub_resolve_download_cache_and_lru(tmp_path):
    """Hub-name resolution (reference from_pretrained.py:168-308 +
    disk_cache.py LRU): first use downloads via fetch_fn, second use hits
    the cache, and the LRU evicts the stalest snapshot under a byte budget."""
    from bloombee_tpu.models.hub import evict_lru, resolve_model_dir

    cache = str(tmp_path / "cache")
    calls = []

    def fake_fetch(name, dest):
        calls.append(name)
        os.makedirs(dest, exist_ok=True)
        with open(os.path.join(dest, "config.json"), "w") as f:
            json.dump({"model_type": "llama", "name": name}, f)
        with open(os.path.join(dest, "model.safetensors"), "wb") as f:
            f.write(b"x" * 1000)

    d1 = resolve_model_dir("org/model-a", cache_dir=cache,
                           max_cache_bytes=0, fetch_fn=fake_fetch)
    assert json.load(open(os.path.join(d1, "config.json")))["name"] == "org/model-a"
    d1_again = resolve_model_dir("org/model-a", cache_dir=cache,
                                 max_cache_bytes=0, fetch_fn=fake_fetch)
    assert d1 == d1_again and calls == ["org/model-a"]  # cache hit

    # local paths pass through untouched
    assert resolve_model_dir(d1, fetch_fn=fake_fetch) == d1

    # second model + a tight budget evicts the least recently used
    import time as _t

    _t.sleep(0.01)
    resolve_model_dir("org/model-b", cache_dir=cache, max_cache_bytes=0,
                      fetch_fn=fake_fetch)
    freed = evict_lru(cache, max_bytes=1500)
    assert freed > 0
    assert not os.path.exists(d1)  # model-a was stalest
    assert os.path.exists(os.path.join(cache, "org--model-b"))
