from bloombee_tpu.models.llama.block import block_forward, init_block_params
from bloombee_tpu.models.llama.config import llama_spec_from_hf

__all__ = ["block_forward", "init_block_params", "llama_spec_from_hf"]
