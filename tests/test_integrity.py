"""Byzantine-robust serving (ISSUE 12): corruption fault injection,
inline sanity gate, cross-replica activation audits, peer quarantine.

Petals names the threat this layer closes: in a public swarm a peer may
return INCORRECT outputs — maliciously or via broken hardware — and the
client would feed them straight into the next span. The correctness bar
here: a seeded liar server is detected and quarantined mid-decode while
the final generation stays token-identical to HF greedy (every lie is
caught BEFORE its token commits), and an honest swarm with every check
forced on produces ZERO rejects/mismatches (no false positives — exact
compares would convict honest ulp drift, hence bbtpu-lint BB007).
"""

import asyncio
import random
import time
import types

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.integrity import SanityGate, tensors_close
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.kv.prefix import out_digest
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.data import RemoteSpanInfo, ServerInfo
from bloombee_tpu.wire import faults, tensor_codec
from bloombee_tpu.wire.faults import (
    FaultPlan,
    FaultRule,
    _is_span_output_reply,
)
from bloombee_tpu.wire.rpc import connect
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import SteppableClock


@pytest.fixture
def stepper():
    """Hand-stepped process clock: the quarantine state machine reads
    clock.monotonic(), so tests advance virtual time instead of sleeping
    — identical transitions, zero wall-clock waits."""
    c = SteppableClock()
    prev = clock.install(c)
    yield c
    clock.install(prev)


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_integ")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefix_cache", True)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


# ------------------------------------------------- corrupt wire action
def _span_output_frame(arr):
    """A frame shaped like a server step reply: "sitem" with tensor metas
    and compute timing in the meta (the corrupt rule's predicate)."""
    m, b = tensor_codec.serialize_tensor(arr, compression=True)
    header = {
        "t": "sitem", "id": 7,
        "meta": {"t_compute_ms": 1.0},
        "tm": [m.to_wire()],
    }
    return header, [b]


def _decode_frame(header, blobs):
    meta = tensor_codec.TensorMeta.from_wire(header["tm"][0])
    return tensor_codec.deserialize_tensor(meta, blobs[0])


def _conn():
    return types.SimpleNamespace(peer=("127.0.0.1", 7000))


def _corrupt_plan(seed, prob=None):
    return FaultPlan(
        [FaultRule(site="send", action="corrupt", method="sitem",
                   prob=prob, count=0,
                   predicate=_is_span_output_reply)],
        seed=seed,
    )


def test_corrupt_keeps_frame_well_formed_and_is_seeded():
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((1, 4, 16)) * 0.02).astype(np.float32)

    def corrupted(seed):
        header, blobs = _span_output_frame(arr)
        plan = _corrupt_plan(seed)
        asyncio.run(plan.on_send(_conn(), header, blobs))
        assert plan.log and plan.log[0][1] == "corrupt"
        return header, blobs

    h1, b1 = corrupted(5)
    # the frame is still WELL-FORMED: valid meta, decodable payload, same
    # geometry — only the numbers changed (detectable solely by the
    # integrity layer, never by the transport)
    out = _decode_frame(h1, b1)
    assert out.shape == arr.shape and out.dtype == arr.dtype
    assert not np.array_equal(
        np.nan_to_num(np.asarray(out)), arr
    ) or not np.isfinite(np.asarray(out)).all()
    # and the digest of the corrupted bytes no longer matches the
    # original's — exactly what the client-side out_digest check sees
    assert out_digest(np.asarray(out)) != out_digest(arr)

    # seeded replay: same seed -> bit-identical corruption; different
    # seed -> a different lie
    h2, b2 = corrupted(5)
    assert b2 == b1 and h2["tm"] == h1["tm"]
    h3, b3 = corrupted(6)
    assert b3 != b1


def test_corrupt_leaves_nonfloat_and_foreign_frames_alone():
    ids = np.arange(12, dtype=np.int32).reshape(1, 12)
    header, blobs = _span_output_frame(ids)
    before = (dict(header), list(header["tm"]), list(blobs))
    plan = _corrupt_plan(1)
    asyncio.run(plan.on_send(_conn(), header, blobs))
    # int tensors ship untouched (corrupting token ids is a different,
    # activation-invisible failure class)
    assert blobs == before[2] and header["tm"] == before[1]

    # frames that are NOT span-output replies (no compute timing in the
    # meta: acks, client->server sends) never match the predicate — a
    # process-wide chaos plan must not poison server-side KV via prefill
    m, b = tensor_codec.serialize_tensor(
        np.ones((1, 2, 4), np.float32), compression=True
    )
    client_send = {"t": "sitem", "id": 1, "meta": {}, "tm": [m.to_wire()]}
    plan2 = _corrupt_plan(1)
    asyncio.run(plan2.on_send(_conn(), client_send, [b]))
    assert not plan2.log


def test_chaos_env_builds_corrupt_rule(monkeypatch):
    monkeypatch.setenv("BBTPU_CHAOS", "1")
    monkeypatch.setenv("BBTPU_CHAOS_CORRUPT_P", "0.25")
    plan = FaultPlan.from_env()
    assert plan is not None
    (rule,) = [r for r in plan.rules if r.action == "corrupt"]
    assert rule.site == "send" and rule.method == "sitem"
    assert rule.prob == 0.25
    assert rule.predicate is _is_span_output_reply


# ------------------------------------------------------------ sanity gate
def test_sanity_gate_envelope_and_nonfinite():
    rng = np.random.default_rng(3)
    g = SanityGate(margin=4.0, warmup=3)
    key = (0, 3)
    base = (rng.standard_normal((1, 1, 64)) * 0.02).astype(np.float32)
    for _ in range(4):
        assert g.check(key, base) is None
    # honest drift well inside the margin is accepted...
    assert g.check(key, base * 1.5) is None
    # ...and updates the envelope; the x64 lie does not
    reason = g.check(key, base * 64)
    assert reason is not None and "rms-envelope" in reason
    # a rejected output must NOT stretch the envelope for the next lie
    assert g.check(key, base * 16) is not None
    # NaN poison is caught regardless of magnitude or warmup
    poisoned = base.copy()
    poisoned[0, 0, 5] = np.nan
    assert g.check(key, poisoned) == "nonfinite"
    assert g.check((1, 2), poisoned) == "nonfinite"  # fresh key too


def test_sanity_gate_warmup_accepts_unconditionally():
    g = SanityGate(margin=4.0, warmup=3)
    # first `warmup` observations establish the envelope, whatever their
    # scale — prefill activations legitimately dwarf decode ones
    big = np.full((1, 1, 8), 100.0, np.float32)
    small = np.full((1, 1, 8), 0.01, np.float32)
    assert g.check((0, 1), big) is None
    assert g.check((0, 1), small) is None
    assert g.check((0, 1), big) is None
    # post-warmup, the envelope (max accepted RMS = 100) holds: 3.9x is
    # inside the 4x margin and, once ACCEPTED, stretches the envelope —
    # so the next lie must clear 4 x 390, not 4 x 100
    assert g.check((0, 1), big * 3.9) is None
    assert g.check((0, 1), big * 20) is not None


# ------------------------------------------------------------ tolerance
def test_tensors_close_is_dtype_aware_never_exact():
    rng = np.random.default_rng(4)
    a = (rng.standard_normal((1, 2, 32))).astype(np.float32)
    # ulp-scale drift (what honest replicas produce: float reductions are
    # batch-width dependent) passes at every wire dtype
    drift = a * (1 + 1e-3)
    assert tensors_close(a, drift, dtype="f32")
    assert tensors_close(a, a + 0.05 * np.abs(a), dtype="bf16")
    # lies don't
    assert not tensors_close(a, a * 64, dtype="bf16")
    nanned = a.copy()
    nanned[0, 0, 0] = np.nan
    assert not tensors_close(a, nanned, dtype="f32")
    # geometry mismatch is an automatic fail, never a crash
    assert not tensors_close(a, a[:, :1], dtype="f32")
    # f32 is tighter than bf16: 5% drift passes bf16, fails f32
    noisy = a * 1.05
    assert tensors_close(a, noisy, dtype="bf16")
    assert not tensors_close(a, noisy, dtype="f32")


def test_out_digest_binds_dtype_shape_and_bytes():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    assert out_digest(a) == out_digest(a.copy())
    assert out_digest(a) != out_digest(a.reshape(4, 3))
    assert out_digest(a) != out_digest(a.astype(np.float64))
    b = a.copy()
    b[0, 0] += 1e-7
    assert out_digest(a) != out_digest(b)


# --------------------------------------------------- quarantine machinery
def _span(peer_id, start, end, **info_kw):
    info_kw.setdefault("host", "127.0.0.1")
    info_kw.setdefault("port", 7000 + hash(peer_id) % 100)
    info_kw.setdefault("throughput", 10.0)
    return RemoteSpanInfo(
        peer_id, start, end,
        ServerInfo(start_block=start, end_block=end, **info_kw),
    )


def _manager(num_blocks=2, **kw):
    kw.setdefault("quarantine_timeout", 0.2)
    kw.setdefault("quarantine_max", 1.0)
    kw.setdefault("rng", random.Random(0))
    return RemoteSequenceManager(None, "uid", num_blocks, **kw)


def test_strikes_accumulate_to_quarantine_and_never_decay():
    m = _manager()
    m.spans = {"a": _span("a", 0, 2), "b": _span("b", 0, 2)}
    assert not m.note_integrity_strike("a")
    assert "a" not in m._quarantine
    # ordinary successes do NOT clear integrity strikes (a lie is
    # evidence of Byzantine behavior, not a transient fault)...
    m.note_peer_ok("a")
    assert m._integrity_strikes["a"] == 1
    # ...so the second strike convicts, however many successes separated
    # the two lies
    assert m.note_integrity_strike("a")
    assert "a" in m._quarantine
    assert m.peers_quarantined == 1
    for _ in range(5):
        assert [s.peer_id for s in m.make_sequence()] == ["b"]


def test_quarantined_peer_excluded_from_standby_pool():
    m = _manager()
    primary = _span("primary", 0, 2, kv_repl=True, page_size=4)
    fast = _span("fast", 0, 2, kv_repl=True, page_size=4,
                 inference_rps=100.0, throughput=100.0)
    slow = _span("slow", 0, 2, kv_repl=True, page_size=4,
                 inference_rps=1.0, throughput=1.0)
    m.spans = {s.peer_id: s for s in (primary, fast, slow)}
    assert m.pick_standby(primary).peer_id == "fast"
    m.quarantine_peer("fast")
    # a lying peer must never receive replicated KV, however attractive
    # its throughput advert
    assert m.pick_standby(primary).peer_id == "slow"
    m.quarantine_peer("slow")
    assert m.pick_standby(primary) is None


def test_quarantine_readmission_keeps_escalation_history(stepper):
    m = _manager(quarantine_timeout=0.05, quarantine_max=10.0)
    m.quarantine_peer("a")
    first = m._quarantine["a"].banned_until - clock.monotonic()
    assert 0.05 * 0.75 <= first <= 0.05 * 1.25 + 0.01
    assert m._integrity_excludes("a", clock.monotonic())
    stepper.advance(0.08)
    # expiry admits exactly one half-open probe; other routes still avoid
    now = clock.monotonic()
    assert not m._integrity_excludes("a", now)
    assert m._integrity_excludes("a", now)
    # the probe succeeds -> readmitted, but the conviction count survives
    m.note_peer_ok("a")
    assert "a" not in m._quarantine
    assert m._quarantine_history["a"] == 1
    # conviction had reset the sanity strikes: fresh evidence re-convicts
    assert "a" not in m._integrity_strikes
    m.quarantine_peer("a")
    st = m._quarantine["a"]
    assert st.strikes == 2  # restored from history, then escalated
    backoff = st.banned_until - clock.monotonic()
    assert backoff >= 0.05 * 2 * 0.74  # doubled base, not from scratch


def test_quarantine_outlives_fault_ban_class(stepper):
    """Quarantine is the LONGEST penalty class: with identical strike
    counts a quarantined peer stays excluded long after a fault-banned
    peer has been re-admitted."""
    m = _manager(ban_timeout=0.05, ban_max=0.05,
                 quarantine_timeout=5.0, quarantine_max=10.0)
    m.ban_peer("crashed")
    m.quarantine_peer("liar")
    stepper.advance(0.08)
    now = clock.monotonic()
    assert not m._ban_excludes("crashed", now)
    assert m._integrity_excludes("liar", now)


# ------------------------------------------------------------------- e2e
async def _greedy_decode(model, session, out, n, dtype=np.int64):
    new = np.zeros((out.shape[0], 0), dtype=dtype)
    for _ in range(n):
        logits = model.logits(out[:, -1:])[:, 0]
        nxt = np.argmax(logits, axis=-1).astype(dtype)[:, None]
        new = np.concatenate([new, nxt], axis=1)
        out = await session.step(model.embed(nxt), ids=nxt)
    return new, out


def test_liar_server_is_quarantined_and_decode_stays_token_identical(
    tiny_model_dir,
):
    """Three whole-model replicas, one a seeded liar advertising the best
    throughput (so routing picks it first — the worst case). With the
    integrity layer + audit_p=1.0 on, the liar must land in quarantine
    and the full generation must match HF greedy token-for-token: every
    lie is caught BEFORE its token commits, so recovery replays from
    clean history."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        liar = _server(
            model_dir, rc(), 0, 3, throughput=100.0, integrity=True,
            liar_p=1.0, liar_seed=7,
        )
        honest = [
            _server(model_dir, rc(), 0, 3, throughput=1.0, integrity=True)
            for _ in range(2)
        ]
        for s in (liar, *honest):
            await s.start()

        input_ids = (np.arange(8)[None, :] * 5 + 3) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        cfg = ClientConfig(
            use_push=False, integrity=True, audit_p=1.0,
            quarantine_timeout=600.0,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(24, 1)
        async with session:
            out = await session.step(model.embed(input_ids), ids=input_ids)
            new, _ = await _greedy_decode(
                model, session, out, 6, dtype=input_ids.dtype
            )
            manager = model.manager
            assert liar.server_id in manager._quarantine, (
                f"liar not quarantined (lied {liar.liar_steps}x, "
                f"{session.sanity_rejects} gate rejects, "
                f"{session.audit_mismatches} audit mismatches)"
            )
            assert manager.peers_quarantined >= 1
            # detection fired through at least one of the two mechanisms
            assert session.sanity_rejects + session.audit_mismatches >= 1
            assert session.integrity_reroutes >= 1
            # the current chain no longer contains the liar
            assert all(
                sp.span.peer_id != liar.server_id
                for sp in session._spans
            )
        got = np.concatenate([input_ids, new], axis=1)
        np.testing.assert_array_equal(got, ref)

        # observability: the liar's own counters ride rpc_info
        conn = await connect("127.0.0.1", liar.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["integrity"] is True
        assert info["liar_steps"] == liar.liar_steps >= 1
        assert info["out_digests_sent"] >= 1
        assert "audit_forwards" in info
        assert "seq_hash_extend_failures" in info
        await conn.close()

        for s in (liar, *honest):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_clean_swarm_zero_false_positives_with_everything_on(
    tiny_model_dir,
):
    """False-positive gate: an HONEST 3-replica swarm with the sanity
    gate + digests + audit_p=1.0 forced on must decode with ZERO rejects
    and ZERO audit mismatches (honest replicas differ in ulps; exact
    compares would convict them — bbtpu-lint BB007), and the integrity
    layer must not change the tokens."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            _server(model_dir, rc(), 0, 3, integrity=True)
            for _ in range(3)
        ]
        for s in servers:
            await s.start()

        input_ids = (np.arange(10)[None, :] * 7 + 1) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 5)

        cfg = ClientConfig(use_push=False, integrity=True, audit_p=1.0)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(24, 1)
        async with session:
            out = await session.step(model.embed(input_ids), ids=input_ids)
            new, _ = await _greedy_decode(
                model, session, out, 5, dtype=input_ids.dtype
            )
            assert session.audits_run >= 1  # the audits actually ran
            assert session.sanity_rejects == 0
            assert session.audit_mismatches == 0
            assert model.manager.peers_quarantined == 0
            assert not model.manager._quarantine
        got = np.concatenate([input_ids, new], axis=1)
        np.testing.assert_array_equal(got, ref)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_clean_spec_decode_zero_false_positives(tiny_model_dir):
    """Speculative decoding under the inline gate (tree steps pass the
    same sanity checks; audits sit out non-committing tree steps): the
    greedy-equals-speculative invariant must hold with integrity forced
    on, with zero rejects."""
    model_dir, _, config = tiny_model_dir

    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            _server(model_dir, rc(), 0, 2, integrity=True),
            _server(model_dir, rc(), 2, 3, integrity=True),
        ]
        for s in servers:
            await s.start()

        cfg = ClientConfig(use_push=False, integrity=True)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(model_dir), branching=(2, 1)
        )
        input_ids = np.arange(5)[None, :]
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=8
        )
        plain_ids = await model.generate(
            input_ids,
            max_new_tokens=spec_ids.shape[1] - input_ids.shape[1],
        )
        np.testing.assert_array_equal(spec_ids, plain_ids)
        assert model.manager.peers_quarantined == 0

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())
