"""Wire protocol: tensor codec roundtrips, RPC unary/stream/push, registry.

Ports the intent of /root/reference/tests/test_lossless_transport.py (codec
roundtrip + gates) plus basic transport-level coverage the reference gets from
hivemind itself.
"""

import asyncio

import ml_dtypes
import numpy as np
import pytest

from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo, ServerState
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.swarm.spans import compute_spans
from bloombee_tpu.wire.rpc import RpcError, RpcServer, connect
from bloombee_tpu.wire.tensor_codec import (
    MIN_COMPRESS_BYTES,
    deserialize_tensor,
    serialize_tensor,
)


@pytest.mark.parametrize(
    "dtype", [np.float32, np.float16, ml_dtypes.bfloat16, np.int32, np.bool_]
)
def test_codec_roundtrip(dtype):
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(33, 257)).astype(dtype)
    meta, payload = serialize_tensor(arr)
    out = deserialize_tensor(meta, payload)
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(
        out.view(np.uint8) if dtype == ml_dtypes.bfloat16 else out,
        arr.view(np.uint8) if dtype == ml_dtypes.bfloat16 else arr,
    )


def test_codec_small_payload_ships_raw():
    arr = np.zeros((10,), np.float32)
    meta, _ = serialize_tensor(arr)
    assert meta.codec == "raw"


def test_codec_compresses_large_redundant_bf16():
    n = MIN_COMPRESS_BYTES  # bytes/2 items -> 2n bytes > threshold
    arr = np.ones((n,), ml_dtypes.bfloat16)
    meta, payload = serialize_tensor(arr)
    assert meta.codec in ("zstd", "zlib") and meta.byte_split
    assert len(payload) < arr.nbytes // 10
    out = deserialize_tensor(meta, payload)
    np.testing.assert_array_equal(out.view(np.uint8), arr.view(np.uint8))


def test_codec_incompressible_ships_raw():
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 255, size=(MIN_COMPRESS_BYTES * 2,), dtype=np.uint8)
    meta, payload = serialize_tensor(arr)
    assert meta.codec == "raw" and len(payload) == arr.nbytes


def test_rpc_unary_stream_push():
    async def run():
        got_pushes = []

        async def echo(meta, tensors):
            return {"echo": meta["x"] + 1}, [t * 2 for t in tensors]

        async def stream_handler(stream):
            # double every item until client half-closes, then send a summary
            n = 0
            while True:
                item = await stream.recv()
                if item is None:
                    break
                meta, tensors = item
                n += 1
                await stream.send({"seq": meta["seq"]}, [tensors[0] + 1])
            await stream.send({"done": True, "count": n})
            await stream.close()

        async def on_push(meta, tensors):
            got_pushes.append((meta, tensors))

        server = RpcServer(
            unary_handlers={"echo": echo},
            stream_handlers={"session": stream_handler},
            push_handlers={"note": on_push},
            host="127.0.0.1",
        )
        await server.start()
        conn = await connect("127.0.0.1", server.port)

        # unary with tensors
        meta, tensors = await conn.call(
            "echo", {"x": 41}, [np.arange(8, dtype=np.float32)]
        )
        assert meta["echo"] == 42
        np.testing.assert_array_equal(tensors[0], np.arange(8) * 2.0)

        # unknown method -> RpcError
        with pytest.raises(RpcError):
            await conn.call("nope", {})

        # bidirectional stream
        stream = await conn.open_stream("session", {"model": "m"})
        for i in range(3):
            await stream.send({"seq": i}, [np.full((4,), i, np.float32)])
        await stream.close()
        outs = []
        while True:
            item = await stream.recv()
            if item is None or item[0].get("done"):
                assert item is None or item[0]["count"] == 3
                break
            outs.append(item)
        assert [m["seq"] for m, _ in outs] == [0, 1, 2]
        np.testing.assert_array_equal(outs[2][1][0], np.full((4,), 3.0))

        # push
        await conn.push("note", {"k": "v"}, [np.ones(2, np.float32)])
        await asyncio.sleep(0.05)
        assert got_pushes and got_pushes[0][0]["k"] == "v"

        await conn.close()
        await server.stop()

    asyncio.run(run())


def test_rpc_concurrent_calls_multiplex():
    async def run():
        async def slow(meta, tensors):
            await asyncio.sleep(meta["delay"])
            return {"v": meta["v"]}, []

        server = RpcServer(unary_handlers={"slow": slow}, host="127.0.0.1")
        await server.start()
        conn = await connect("127.0.0.1", server.port)
        r = await asyncio.gather(
            conn.call("slow", {"delay": 0.05, "v": 1}),
            conn.call("slow", {"delay": 0.0, "v": 2}),
        )
        assert [m["v"] for m, _ in r] == [1, 2]
        await conn.close()
        await server.stop()

    asyncio.run(run())


def test_registry_announce_fetch_expire():
    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        client = RegistryClient("127.0.0.1", reg.port)

        info_a = ServerInfo(host="127.0.0.1", port=1111, throughput=5.0)
        info_b = ServerInfo(host="127.0.0.1", port=2222, throughput=3.0)
        await client.declare_blocks("model", "A", range(0, 3), info_a, 30.0)
        await client.declare_blocks("model", "B", range(2, 5), info_b, 0.05)

        infos = await client.get_module_infos("model", range(0, 5))
        spans = compute_spans(infos)
        assert (spans["A"].start, spans["A"].end) == (0, 3)
        assert (spans["B"].start, spans["B"].end) == (2, 5)
        assert spans["A"].server_info.throughput == 5.0

        await asyncio.sleep(0.06)  # B's records expire (the failure detector)
        infos = await client.get_module_infos("model", range(0, 5))
        spans = compute_spans(infos)
        assert "B" not in spans and "A" in spans

        # revoke = clean OFFLINE announce
        await client.revoke_blocks("model", "A", range(0, 3))
        infos = await client.get_module_infos("model", range(0, 5))
        assert compute_spans(infos) == {}

        await client.close()
        await reg.stop()

    asyncio.run(run())


def test_compute_spans_skips_offline():
    info = ServerInfo(state=ServerState.JOINING)
    infos = [ModuleInfo(uid="m.0", servers={"X": info})]
    assert compute_spans(infos) == {}


def test_transport_stats_counters():
    """Codec profiling counters (reference lossless_transport profiling
    channels): tx/rx tensor counts, raw vs wire bytes, compression ratio."""
    import numpy as np

    from bloombee_tpu.wire.tensor_codec import (
        deserialize_tensor,
        reset_transport_stats,
        serialize_tensor,
        transport_stats,
    )

    reset_transport_stats()
    big = np.zeros((256, 256), np.float32)  # compressible, above min size
    small = np.ones((4,), np.float32)  # ships raw
    for arr in (big, small):
        meta, blob = serialize_tensor(arr)
        out = deserialize_tensor(meta, blob)
        np.testing.assert_array_equal(out, arr)
    st = transport_stats()
    assert st["tx"]["n"] == 2 and st["rx"]["n"] == 2
    assert st["tx"]["compressed"] == 1  # only the big one
    assert st["tx"]["raw_bytes"] == big.nbytes + small.nbytes
    assert st["tx"]["wire_bytes"] < st["tx"]["raw_bytes"]
    assert 0.0 < st["tx"]["ratio"] < 1.0
    assert st["tx"]["s"] >= 0.0


def test_flow_limiter_adapts():
    """The adaptive push limiter grows under queue pressure with fast sends,
    shrinks under slow sends or failures, and stays within bounds."""
    import asyncio

    from bloombee_tpu.wire.flow import FlowLimiter

    async def drive(lim, n, send_s=0.0, fail=False, waiters=1):
        async def one():
            try:
                async with lim.slot():
                    if send_s:
                        await asyncio.sleep(send_s)
                    if fail:
                        raise OSError("boom")
            except OSError:
                pass

        for _ in range(n):
            await asyncio.gather(*[one() for _ in range(waiters)])

    async def run():
        # queue pressure with instant sends -> limit grows
        lim = FlowLimiter(initial=1, decide_every=4, wait_up_ms=0.0)
        await drive(lim, 16, waiters=4)
        assert lim.limit > 1, lim.limit

        # consecutive failures -> limit shrinks to the floor, never below
        lim2 = FlowLimiter(initial=3, lo=1, decide_every=2)
        await drive(lim2, 32, fail=True)
        assert lim2.limit == 1, lim2.limit

        # slow sends with no waiters -> backpressure shrink
        lim3 = FlowLimiter(
            initial=4, decide_every=2, send_slow_ms=1.0
        )
        await drive(lim3, 8, send_s=0.005)
        assert lim3.limit < 4, lim3.limit

        # concurrent holders must not share timing state: a slow send
        # overlapped by fast ones still registers as slow
        lim4 = FlowLimiter(initial=4, decide_every=1000)

        async def slow():
            async with lim4.slot():
                await asyncio.sleep(0.05)

        async def fast():
            await asyncio.sleep(0.01)  # start after slow() holds its slot
            async with lim4.slot():
                pass

        await asyncio.gather(slow(), fast(), fast(), fast())
        # EWMA saw one 50 ms sample among ~0 ms ones; with alpha=0.2 and
        # the slow sample landing last it must remain clearly visible
        assert lim4.ewma_send_ms > 5.0, lim4.ewma_send_ms

    asyncio.run(run())
