"""Server-side draft-tree pruning (MidLMHead + probability pruner).

Port of /root/reference/src/bloombee/server/speculative_pruner/
(pruner_manager.py:13-186, simple_probability_pruner.py:11-241,
mid_layer_LM_head.py): a small trainable linear head scores MID-network
hidden states of draft-tree nodes; children whose renormalized
parent-conditioned probability clears a threshold are kept, the rest are
pruned before the remaining (deeper) blocks run — cutting wasted tree
compute and downstream wire bytes.

This module provides the jitted scoring head and the keep-index math with
the reference's semantics (keep_indices padded with -1, parents always kept
when any descendant survives). Wire integration (shrinking the tree
mid-chain) lands with the micro-batch/multiplexing work.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.spec.tree import DraftTree


class MidLMHead:
    """Small linear head over mid-network hidden states (trainable online in
    the reference via lm_head_trainer; here initialized from the real LM
    head or randomly and updatable by assignment). An optional RMS norm
    weight is applied first ("logit lens"): raw mid-layer hidden has a
    growing scale that makes untrained-head softmaxes uninformative."""

    def __init__(self, weight: jax.Array, norm=None, eps: float = 1e-5):
        self.weight = weight  # [D, V]
        self.norm = norm  # [D] or None
        self.eps = eps

    @staticmethod
    @jax.jit
    def _probs(weight, norm, eps, hidden):
        if norm is not None:
            from bloombee_tpu.ops import rms_norm

            hidden = rms_norm(hidden, norm, eps)
        logits = (hidden @ weight).astype(jnp.float32)
        return jax.nn.softmax(logits, axis=-1)

    def probs(self, hidden: np.ndarray) -> np.ndarray:
        """hidden [N, D] -> softmax rows [N, V]; per-token gathering against
        the parent's distribution happens in the pruner."""
        return np.asarray(
            self._probs(self.weight, self.norm, self.eps, jnp.asarray(hidden))
        )


def _cap_kept_by_score(
    tree: DraftTree, keep: np.ndarray, scores: np.ndarray, cap: int
) -> np.ndarray:
    """Shrink a keep mask to `cap` nodes by repeatedly dropping the
    lowest-SCORING kept leaf (a kept node with no kept children), so the
    survivors are the best-scoring tree-consistent subset. Truncating by
    node index would discard high-score deep nodes just for being drafted
    late (advisor finding, round 2).

    Heap-driven: dropping a leaf may expose its parent as the new
    lowest-scoring leaf, so each drop is a pop + at most one push —
    O(k log k) total instead of the previous full leaf rescan per drop
    (O(k^2), flagged in round 4 as a compute-path risk for larger trees).
    Ties resolve by (score, index), matching the old argmin's
    first-lowest-index choice."""
    import heapq

    n_kept = int(keep.sum())
    if n_kept <= cap:
        return keep
    kept_child_count = np.zeros(tree.size, dtype=np.int32)
    for c in np.nonzero(keep)[0]:
        parent = int(tree.parents[c])
        if parent >= 0 and keep[parent]:
            kept_child_count[parent] += 1
    heap = [
        (float(scores[i]), int(i))
        for i in np.nonzero(keep)[0]
        if kept_child_count[i] == 0
    ]
    heapq.heapify(heap)
    while n_kept > cap and heap:
        _, i = heapq.heappop(heap)
        if not keep[i] or kept_child_count[i] != 0:
            continue  # stale entry (node re-pushed or no longer a leaf)
        keep[i] = False
        n_kept -= 1
        parent = int(tree.parents[i])
        if parent >= 0 and keep[parent]:
            kept_child_count[parent] -= 1
            if kept_child_count[parent] == 0:
                heapq.heappush(heap, (float(scores[parent]), parent))
    return keep


@dataclasses.dataclass
class SimpleProbabilityPruner:
    """Keep children whose parent-conditioned renormalized probability
    clears `threshold` (reference simple_probability_pruner.py)."""

    threshold: float = 0.05
    max_keep: int | None = None

    def keep_indices(
        self,
        tree: DraftTree,
        probs: np.ndarray,  # [T+1?, V]: row 0.. per node position; row for
        # the root level comes from the last committed token (index -1 via
        # `root_probs`)
        root_probs: np.ndarray,  # [V]
    ) -> np.ndarray:
        """Returns kept linear indices, padded with -1 to max_keep (or tree
        size). A node is kept iff its own conditional prob clears the
        threshold AND its parent is kept (subtree pruning)."""
        t = tree.size
        keep = np.zeros(t, dtype=bool)
        node_p = np.zeros(t, dtype=np.float64)  # for score-ordered capping
        # renormalize within each sibling group
        for parent in [-1] + list(range(t)):
            children = tree.children_of(parent)
            if len(children) == 0:
                continue
            dist = root_probs if parent < 0 else probs[parent]
            child_p = np.asarray(
                [dist[int(tree.tokens[c])] for c in children], np.float64
            )
            z = child_p.sum()
            if z <= 0:
                continue
            child_p = child_p / z
            for c, p in zip(children, child_p):
                parent_ok = parent < 0 or keep[parent]
                keep[c] = parent_ok and (p >= self.threshold)
                node_p[c] = p
        cap = self.max_keep or t
        keep = _cap_kept_by_score(tree, keep, node_p, cap)
        kept = np.nonzero(keep)[0]
        out = np.full(cap, -1, dtype=np.int32)
        out[: len(kept)] = kept
        return out


class MidHeadTrainer:
    """Online trainer for the MidLMHead (reference lm_head_trainer.py): SGD
    on cross-entropy between the head's prediction at a node's mid hidden
    and the token the FULL model actually chose there (the accepted child).
    Save/load round-trips the weight as .npz (reference
    adaptive_neural_pruner.save_model/load_model:497-515)."""

    def __init__(self, head: MidLMHead, lr: float = 1e-3):
        self.head = head
        self.lr = lr
        self.steps = 0

    @staticmethod
    @jax.jit
    def _step(weight, norm, eps, lr, hidden, targets):
        """targets == -1 marks padding rows (batches are padded to pow2
        buckets so live serving doesn't recompile per pair count)."""

        def loss_fn(w):
            h = hidden
            if norm is not None:
                from bloombee_tpu.ops import rms_norm

                h = rms_norm(h, norm, eps)
            logits = (h @ w).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            valid = targets >= 0
            safe = jnp.where(valid, targets, 0)
            token_lp = jnp.take_along_axis(logp, safe[:, None], axis=-1)[:, 0]
            return -(token_lp * valid).sum() / jnp.maximum(valid.sum(), 1)

        loss, g = jax.value_and_grad(loss_fn)(weight)
        return weight - lr * g, loss

    def train_step(self, hidden: np.ndarray, targets: np.ndarray) -> float:
        """hidden [N, D] mid states, targets [N] full-model tokens."""
        n = len(targets)
        if n == 0:
            return 0.0
        from bloombee_tpu.runtime.executor import next_pow2

        nb = next_pow2(n, floor=4)
        h_pad = np.zeros((nb, hidden.shape[1]), dtype=np.float32)
        h_pad[:n] = hidden
        t_pad = np.full((nb,), -1, dtype=np.int32)
        t_pad[:n] = targets
        w, loss = self._step(
            self.head.weight, self.head.norm, self.head.eps, self.lr,
            jnp.asarray(h_pad), jnp.asarray(t_pad),
        )
        self.head.weight = w
        self.steps += 1
        return float(loss)

    @staticmethod
    def ckpt_path(path: str) -> str:
        """np.savez appends .npz when missing — normalize so save and the
        resume-existence check agree on one file name."""
        return path if path.endswith(".npz") else path + ".npz"

    def save(self, path: str) -> None:
        import os

        path = self.ckpt_path(path)
        arrays = {"weight": np.asarray(self.head.weight)}
        if self.head.norm is not None:
            arrays["norm"] = np.asarray(self.head.norm)
        tmp = f"{path}.tmp.npz"
        np.savez(tmp, steps=self.steps, eps=self.head.eps, **arrays)
        os.replace(tmp, path)  # atomic: a crash can't leave a torn file

    @classmethod
    def load(cls, path: str, lr: float = 1e-3, dtype=None) -> "MidHeadTrainer":
        data = np.load(cls.ckpt_path(path))
        weight = jnp.asarray(data["weight"])
        norm = jnp.asarray(data["norm"]) if "norm" in data else None
        if dtype is not None:
            weight = weight.astype(dtype)
            norm = norm.astype(dtype) if norm is not None else None
        head = MidLMHead(weight, norm, float(data["eps"]))
        trainer = cls(head, lr=lr)
        trainer.steps = int(data["steps"])
        return trainer


class PrunerManager:
    """Lazy-init + method dispatch (reference pruner_manager.py +
    pruner_factory.py): owns the MidLMHead and the active pruning strategy
    ("simple" probability rule or the "neural" learned scorer)."""

    def __init__(self, threshold: float = 0.05, method: str = "simple",
                 neural_params: dict | None = None):
        self._head: MidLMHead | None = None
        self.method = method
        if method == "neural":
            self._pruner = AdaptiveNeuralPruner(
                neural_params
                if neural_params is not None else init_neural_params()
            )
        elif method == "simple":
            self._pruner = SimpleProbabilityPruner(threshold=threshold)
        else:
            raise ValueError(f"unknown pruner method {method!r}")

    def set_request_threshold(self, threshold: float) -> None:
        """The wire threshold tunes the probability rule only; the neural
        pruner's sigmoid cutoff is a server-side knob."""
        if isinstance(self._pruner, SimpleProbabilityPruner):
            self._pruner.threshold = threshold

    def ensure_head(
        self, lm_head_weight, norm=None, eps: float = 1e-5
    ) -> MidLMHead:
        if self._head is None:
            self._head = MidLMHead(
                jnp.asarray(lm_head_weight),
                None if norm is None else jnp.asarray(norm),
                eps,
            )
        return self._head

    def prune(
        self,
        tree: DraftTree,
        hidden: np.ndarray,  # [T, D] mid-network hidden states of the nodes
        root_hidden: np.ndarray,  # [D] last committed token's hidden
        lm_head_weight,
    ) -> np.ndarray:
        head = self.ensure_head(lm_head_weight)
        all_rows = head.probs(
            np.concatenate([root_hidden[None], hidden], axis=0)
        )
        return self._pruner.keep_indices(tree, all_rows[1:], all_rows[0])


def node_features(
    tree: DraftTree, probs: np.ndarray, root_probs: np.ndarray
) -> np.ndarray:
    """Per-node probability features (reference adaptive_neural_pruner.py
    `_compute_prob_features_batched`): from the PARENT's distribution at
    each node — [max_prob, normalized_entropy, log_ratio(own token vs
    max)]. Shape [T, 3] float32."""
    t = tree.size
    v = probs.shape[-1]
    eps = 1e-9
    feats = np.zeros((t, 3), dtype=np.float32)
    log_v = np.log(v)
    # siblings share a parent: compute each distinct parent distribution's
    # (max, entropy) once, not once per child — the entropy pass is a full
    # vocab sweep and this runs per row per speculative step
    stats: dict[int, tuple[float, float]] = {}
    for c in range(t):
        parent = int(tree.parents[c])
        dist = root_probs if parent < 0 else probs[parent]
        if parent not in stats:
            d64 = np.asarray(dist, np.float64)
            stats[parent] = (
                float(d64.max()),
                float(-(d64 * np.log(d64 + eps)).sum()) / log_v,
            )
        mx, ent = stats[parent]
        p_tok = float(dist[int(tree.tokens[c])])
        feats[c] = (mx, ent, np.log((p_tok + eps) / (mx + eps)))
    return feats


def init_neural_params(seed: int = 0, hidden: int = 16) -> dict:
    """Tiny keep/prune MLP (reference NodePruner quality path): 3 features
    -> hidden -> 1 sigmoid score. The output bias starts positive so an
    untrained net KEEPS nodes (prune aggressiveness must be learned, not
    default)."""
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.normal(0, 0.5, (3, hidden)), jnp.float32),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jnp.asarray(rng.normal(0, 0.5, (hidden, 1)), jnp.float32),
        "b2": jnp.full((1,), 1.5, jnp.float32),
    }


@jax.jit
def _neural_scores(params: dict, feats: jax.Array) -> jax.Array:
    h = jnp.tanh(feats @ params["w1"] + params["b1"])
    return jax.nn.sigmoid((h @ params["w2"] + params["b2"])[:, 0])


@dataclasses.dataclass
class AdaptiveNeuralPruner:
    """MLP-scored pruning (reference adaptive_neural_pruner.py:41-519):
    same keep_indices contract as SimpleProbabilityPruner, but the decision
    comes from a learned score over probability features instead of a fixed
    probability threshold. The sigmoid cutoff is the server's own knob —
    the wire threshold (tuned for the probability rule) does not apply."""

    params: dict
    threshold: float = 0.5  # sigmoid cutoff
    max_keep: int | None = None

    def keep_indices(
        self, tree: DraftTree, probs: np.ndarray, root_probs: np.ndarray
    ) -> np.ndarray:
        t = tree.size
        feats = node_features(tree, probs, root_probs)
        scores = np.asarray(_neural_scores(self.params, jnp.asarray(feats)))
        keep = np.zeros(t, dtype=bool)
        for c in range(t):
            parent = int(tree.parents[c])
            parent_ok = parent < 0 or keep[parent]
            keep[c] = parent_ok and scores[c] >= self.threshold
        if not keep.any():
            # never prune the whole tree: keep the highest-scoring root
            # child so generation always advances (reference pads with the
            # best node)
            roots = tree.children_of(-1)
            if len(roots):
                keep[int(roots[int(np.argmax(scores[roots]))])] = True
        cap = self.max_keep or t
        keep = _cap_kept_by_score(tree, keep, scores, cap)
        kept = np.nonzero(keep)[0]
        out = np.full(cap, -1, dtype=np.int32)
        out[: len(kept)] = kept
        return out


class NeuralPrunerTrainer:
    """Online BCE training of the keep/prune MLP from accepts (reference
    collect_training_data + train loop): accepted-path nodes are positives,
    the rest of the drafted tree negatives."""

    def __init__(self, pruner: AdaptiveNeuralPruner, lr: float = 5e-3):
        self.pruner = pruner
        self.lr = lr
        self.steps = 0

    @staticmethod
    @jax.jit
    def _step(params, lr, feats, labels, valid):
        def loss_fn(p):
            h = jnp.tanh(feats @ p["w1"] + p["b1"])
            logits = (h @ p["w2"] + p["b2"])[:, 0]
            per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
                jnp.exp(-jnp.abs(logits))
            )
            return (per * valid).sum() / jnp.maximum(valid.sum(), 1)

        loss, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda w, gw: w - lr * gw, params, g), loss

    def train_step(self, feats: np.ndarray, labels: np.ndarray) -> float:
        n = len(labels)
        if n == 0:
            return 0.0
        from bloombee_tpu.runtime.executor import next_pow2

        nb = next_pow2(n, floor=8)
        f_pad = np.zeros((nb, 3), dtype=np.float32)
        f_pad[:n] = feats
        l_pad = np.zeros((nb,), dtype=np.float32)
        l_pad[:n] = labels
        v_pad = np.zeros((nb,), dtype=np.float32)
        v_pad[:n] = 1.0
        new, loss = self._step(
            self.pruner.params, self.lr, jnp.asarray(f_pad),
            jnp.asarray(l_pad), jnp.asarray(v_pad),
        )
        self.pruner.params = new
        self.steps += 1
        return float(loss)

    def save(self, path: str) -> None:
        import os

        path = MidHeadTrainer.ckpt_path(path)
        tmp = f"{path}.tmp.npz"
        np.savez(
            tmp, steps=self.steps,
            **{k: np.asarray(v) for k, v in self.pruner.params.items()},
        )
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, lr: float = 5e-3) -> "NeuralPrunerTrainer":
        data = np.load(MidHeadTrainer.ckpt_path(path))
        params = {
            k: jnp.asarray(data[k]) for k in ("w1", "b1", "w2", "b2")
        }
        trainer = cls(AdaptiveNeuralPruner(params), lr=lr)
        trainer.steps = int(data["steps"])
        return trainer
