"""GPipe micro-batch pipeline over the "pp" mesh axis, inside one jit.

The swarm-level pipeline (client chains server spans over the wire, with
rpc_push between stages) is the reference's core design; THIS module is the
intra-jit equivalent for a multi-chip host: stacked span params are sharded
over "pp" on the layer dim, each stage runs its local layers, and hidden
states hop stage-to-stage via lax.ppermute over ICI. Micro-batches fill the
pipe GPipe-style: M micro-batches over P stages take M + P - 1 ticks
(reference analogue: micro-batch pipelining, SURVEY.md section 2.8 row 2).

Differentiable end-to-end (scan + ppermute), so the training step backprops
straight through the pipeline schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.parallel.spmd import spmd_span_forward


def gpipe_forward(
    stacked_local: dict,  # this stage's local layer shards
    micro_hidden: jax.Array,  # [M, mb, C, D] micro-batched input (all stages
    # hold identical copies; only stage 0 injects)
    *,
    spec: ModelSpec,
    pp_axis: str = "pp",
    sp_axis: str = "sp",
    tp_axis: str = "tp",
) -> jax.Array:
    """Returns [M, mb, C, D] outputs, valid (and identical) on all pp ranks."""
    p = lax.axis_size(pp_axis)
    rank = lax.axis_index(pp_axis)
    m, mb, c, d = micro_hidden.shape
    ticks = m + p - 1

    fwd = [(j, (j + 1) % p) for j in range(p)]  # stage i -> i+1

    def tick(carry, t):
        h_prev, outputs = carry
        # stage 0 injects micro-batch t (zeros once the pipe drains)
        inject = jnp.where(
            t < m, micro_hidden[jnp.minimum(t, m - 1)], jnp.zeros((mb, c, d), micro_hidden.dtype)
        )
        h_in = jnp.where(rank == 0, inject, h_prev)
        h_out = spmd_span_forward(
            stacked_local, h_in, spec=spec, sp_axis=sp_axis, tp_axis=tp_axis
        )
        # last stage finishes micro-batch t - (p - 1) at tick t
        out_idx = t - (p - 1)
        outputs = jnp.where(
            (rank == p - 1) & (out_idx >= 0),
            lax.dynamic_update_index_in_dim(
                outputs, h_out, jnp.maximum(out_idx, 0), axis=0
            ),
            outputs,
        )
        h_next = lax.ppermute(h_out, pp_axis, fwd)
        return (h_next, outputs), None

    h0 = jnp.zeros((mb, c, d), micro_hidden.dtype)
    out0 = jnp.zeros_like(micro_hidden)
    (_, outputs), _ = lax.scan(
        tick, (h0, out0), jnp.arange(ticks)
    )
    # broadcast the last stage's outputs to every pp rank (zeros elsewhere)
    return lax.psum(outputs, pp_axis)
