"""bloombee_tpu: a TPU-native decentralized LLM serving and fine-tuning framework.

Capabilities mirror ai-decentralized/BloomBee (see /root/repo/SURVEY.md): a model's
transformer blocks are split across a swarm of worker servers; the client holds only
embeddings + final norm + LM head; decode ships hidden states through a chain of
servers that keep per-session paged KV caches server-side.

The design is JAX/XLA-first: blocks are pure functions jitted over bucketed static
shapes, KV lives in a paged device arena updated functionally with donation,
intra-server parallelism is a `jax.sharding.Mesh` with sharding annotations (XLA
inserts the collectives), and the inter-server plane is an asyncio wire protocol.
"""

from bloombee_tpu.version import __version__

__all__ = ["__version__"]
