"""Model families.

Mirrors /root/reference/src/bloombee/models/: each family provides a config
(HF config -> ModelSpec mapping), a block implementation (pure jax function), and
weight conversion from HF checkpoints. Registration happens via
`bloombee_tpu.models.auto` (reference: utils/auto_config.py:82-100).
"""

from bloombee_tpu.models.spec import ModelSpec

__all__ = ["ModelSpec"]
