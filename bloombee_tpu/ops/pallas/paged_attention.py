"""Paged decode attention (Pallas TPU kernel).

The dense decode path gathers every page of context into a contiguous
[B, S, Hkv, hd] buffer each step (kv/arena.py gather_pages) and then runs
masked attention over it — two full passes over the context's HBM bytes per
step. This kernel instead streams K/V pages straight out of the paged arena
(one pass): the page table rides in as a scalar-prefetch operand and steers
each grid step's K/V BlockSpec index map to the right physical page, with
online-softmax stats carried in VMEM scratch across the page dimension.
Covers the decode-attention role of the reference's fused kernels
(/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:733
`mha_gen_llama`), built vLLM-paged-attention-style for the TPU memory
hierarchy.

Kernel layout note (Mosaic constraint): a block may not squeeze the
second-to-last array dimension, so blocking one KV head at a time out of the
[tokens, Hkv, hd] arena is not lowerable. Instead each grid step loads one
whole page ACROSS heads as a [page_size*Hkv, hd] block (a free reshape of
the arena) and computes every query head against every row in ONE MXU
matmul; rows belonging to a different KV-head group are masked off in the
logits. Decode attention is HBM-bandwidth-bound — the x Hkv extra FLOPs are
noise, and the bytes read are exactly one pass over the context.

Scope: four kernels share the online-softmax page-streaming machinery.
`paged_decode_attention` covers single-token decode (T=1; per-sequence
lengths masked per page, sliding windows in-kernel with whole-page skips);
`paged_decode_attention_int4` is its in-VMEM-dequant variant for
int4-quantized arenas; `paged_chunk_attention` covers T>1 steps —
tree-verify steps (the [T, T] tree mask applied in-kernel) and short
multi-token chunks below flash's T>=128 domain; `paged_ragged_attention`
covers mixed-batch steps (N decode rows plus one prefill-chunk row-group
packed raggedly, per-row owning sequence and position) in one grid launch
over the cross-session page-table view. ALiBi, logit soft-caps, and
tree+window combinations take the dense path (the executor checks
eligibility host-side, like the flash prefill kernel).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _online_softmax_body(
    load_kv,  # () -> (k [rows, hd], v [rows, hd]) f32 for the current page
    lens_ref, win_ref, q_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale, page_size, n_pages, hkv, g,
):
    """The page-streaming online-softmax state machine shared by the dense
    and int4 kernels (they differ ONLY in how a K/V page is materialized).

    - block row r holds token (r // hkv) of the page for kv head (r % hkv)
      (row-major flatten of [page_size, Hkv]); query head i belongs to kv
      head i // g. Positions past `length` (page-table padding included)
      and off-group rows mask to NEG before the online-softmax max.
    - sliding window: the decode query sits at position length-1 and sees
      keys in [length - win, length) (matching attend_paged's
      `key_pos > q_pos - window`); win == 0 means full attention. Pages
      wholly below the window are skipped outright — for long contexts
      that is most of them, which is the point of a sliding window.
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    h = hkv * g
    rows = page_size * hkv

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lens_ref[b]
    win = win_ref[0]
    low = jnp.where(win > 0, jnp.maximum(length - win, 0), 0)
    r = jax.lax.broadcasted_iota(jnp.int32, (h, rows), 1)
    qh = jax.lax.broadcasted_iota(jnp.int32, (h, rows), 0)
    pos = j * page_size + r // hkv
    own = (r % hkv) == (qh // g)
    page_live = (j * page_size < length) & ((j + 1) * page_size > low)

    @pl.when(page_live)
    def _update():
        q = q_ref[...].astype(jnp.float32) * scale
        k, v = load_kv()
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [H, page_size * Hkv]
        mask = own & (pos < length) & (pos >= low)
        logits = jnp.where(mask, logits, NEG)
        m = m_scr[...]
        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new) * mask.astype(jnp.float32)
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + p.sum(axis=1, keepdims=True)
        # off-group p entries are exactly zero, so contracting against ALL
        # rows picks out each head's own V rows
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        # fully-masked rows (zero-length padding sequences) divide by eps
        # and emit zeros, which the executor drops with the pad rows
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


def _make_kv_index(page_size: int):
    """Index map steering each grid step's K/V block to the right physical
    page. Out-of-window grid steps must not cost HBM bandwidth: clamp the
    logical page to the first in-window page, so dead steps re-name the
    same block and Pallas elides the duplicate DMA entirely (their compute
    is skipped by pl.when(page_live) in the kernel)."""

    def kv_index(bi, j, pt, ln, wn):
        first = jnp.where(
            wn[0] > 0,
            jnp.maximum(ln[bi] - wn[0], 0) // page_size,
            0,
        )
        return (pt[bi, jnp.maximum(j, first)], 0, 0)

    return kv_index


def _kernel(
    pt_ref,  # [B, NP] i32 scalar prefetch: logical page j of seq b
    lens_ref,  # [B] i32 scalar prefetch: context length per sequence
    win_ref,  # [1] i32 scalar prefetch: sliding window (0 = full attention)
    q_ref,  # [H, hd] — every query head of this sequence
    k_ref,  # [page_size * Hkv, hd] — current physical page, ALL kv heads
    v_ref,  # [page_size * Hkv, hd]
    o_ref,  # [H, hd]
    m_scr,  # [H, 1] f32
    l_scr,  # [H, 1] f32
    acc_scr,  # [H, hd] f32
    *,
    scale: float,
    page_size: int,
    n_pages: int,
    hkv: int,
    g: int,  # query heads per kv head (H = hkv * g)
):
    def load_kv():
        return k_ref[...].astype(jnp.float32), v_ref[...].astype(jnp.float32)

    _online_softmax_body(
        load_kv, lens_ref, win_ref, q_ref, o_ref, m_scr, l_scr, acc_scr,
        scale=scale, page_size=page_size, n_pages=n_pages, hkv=hkv, g=g,
    )


def _int4_kernel(
    pt_ref,  # [B, NP] i32 scalar prefetch
    lens_ref,  # [B] i32
    win_ref,  # [1] i32
    q_ref,  # [H, hd] — PERMUTED head dim (evens then odds)
    kc_ref,  # [page_size * Hkv, hd // 2] u8 int4 codes, current page
    ks_ref,  # [page_size * Hkv, groups] f16 scales
    kz_ref,  # [page_size * Hkv, groups] f16 zeros
    vc_ref,
    vs_ref,
    vz_ref,
    o_ref,  # [H, hd] — PERMUTED
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    page_size: int,
    n_pages: int,
    hkv: int,
    g: int,
    groups: int,
):
    """int4 variant of _kernel: the shared online-softmax body runs over
    pages dequantized in VMEM (reference TorchCompressedDevice decompress,
    compression.py:163-210). Nibble unpack avoids lane interleaving: low
    nibbles are the EVEN original head positions and high nibbles the ODD
    ones, so concat(lo, hi) is the dequantized row in a permuted head
    order — the caller permutes q and un-permutes the output instead.
    Group-wise scales stay compact: original group i covers permuted lanes
    [i*gs/2, (i+1)*gs/2) in each half (evens of a contiguous group are
    contiguous), so dequant is an unrolled per-group slice-scale-concat."""
    half = kc_ref.shape[-1]
    per = half // groups  # permuted lanes per original group, per half

    def deq(codes_ref, s_ref, z_ref):
        codes = codes_ref[...]
        s = s_ref[...].astype(jnp.float32)
        z = z_ref[...].astype(jnp.float32)
        lo = (codes & 0xF).astype(jnp.float32)
        hi = (codes >> 4).astype(jnp.float32)
        halves = []
        for nib in (lo, hi):
            parts = [
                nib[:, i * per : (i + 1) * per] * s[:, i : i + 1]
                + z[:, i : i + 1]
                for i in range(groups)
            ]
            halves.append(
                parts[0] if len(parts) == 1
                else jnp.concatenate(parts, axis=-1)
            )
        return jnp.concatenate(halves, axis=-1)  # [rows, hd] permuted

    def load_kv():
        return deq(kc_ref, ks_ref, kz_ref), deq(vc_ref, vs_ref, vz_ref)

    _online_softmax_body(
        load_kv, lens_ref, win_ref, q_ref, o_ref, m_scr, l_scr, acc_scr,
        scale=scale, page_size=page_size, n_pages=n_pages, hkv=hkv, g=g,
    )


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "scale", "interpret"),
)
def paged_decode_attention_int4(
    q: jax.Array,  # [B, H, hd]
    k_slab,  # QuantSlab (codes [S_tot, Hkv, hd/2] u8, scale/zero f16)
    v_slab,
    page_table: jax.Array,
    lens: jax.Array,
    page_size: int,
    scale: float | None = None,
    interpret: bool = False,
    window=0,
) -> jax.Array:
    """Paged decode attention straight off an int4-quantized arena: one HBM
    pass over ~1/3 the bytes of the bf16 slab (codes + group scales), with
    dequantization in VMEM."""
    b, h, hd = q.shape
    s_tot, hkv = k_slab.codes.shape[0], k_slab.codes.shape[1]
    if h % hkv:
        raise ValueError(f"H={h} must be a multiple of Hkv={hkv}")
    if s_tot % page_size:
        raise ValueError(f"arena slots {s_tot} % page_size {page_size}")
    g = h // hkv
    groups = k_slab.scale.shape[-1]
    n_pages = page_table.shape[1]
    if scale is None:
        scale = hd**-0.5
    rows = page_size * hkv

    # permuted head order: evens then odds (see kernel docstring)
    q_perm = jnp.concatenate([q[..., 0::2], q[..., 1::2]], axis=-1)

    def pages(x, last):
        return x.reshape(-1, rows, last)

    kc, ks, kz = (
        pages(k_slab.codes, hd // 2),
        pages(k_slab.scale, groups),
        pages(k_slab.zero, groups),
    )
    vc, vs, vz = (
        pages(v_slab.codes, hd // 2),
        pages(v_slab.scale, groups),
        pages(v_slab.zero, groups),
    )

    kv_index = _make_kv_index(page_size)

    def q_index(bi, j, pt, ln, wn):
        return (bi, 0, 0)

    kv_spec = lambda last: pl.BlockSpec((None, rows, last), kv_index)  # noqa: E731
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_pages),
        in_specs=[
            pl.BlockSpec((None, h, hd), q_index),
            kv_spec(hd // 2), kv_spec(groups), kv_spec(groups),
            kv_spec(hd // 2), kv_spec(groups), kv_spec(groups),
        ],
        out_specs=pl.BlockSpec((None, h, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(
            _int4_kernel, scale=scale, page_size=page_size, n_pages=n_pages,
            hkv=hkv, g=g, groups=groups,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lens.astype(jnp.int32), win_arr,
        q_perm, kc, ks, kz, vc, vs, vz,
    )
    # un-permute: permuted lane i < hd/2 holds original 2i; i >= hd/2 holds
    # original 2(i - hd/2) + 1
    inv = np.empty((hd,), np.int32)
    inv[0::2] = np.arange(hd // 2)
    inv[1::2] = np.arange(hd // 2) + hd // 2
    return out[..., jnp.asarray(inv)]


def _chunk_kernel(
    pt_ref,  # [B, NP] i32 scalar prefetch
    lens_ref,  # [B] i32 scalar prefetch (lens INCLUDE the T new tokens)
    meta_ref,  # [2] i32 scalar prefetch: [window (0 = full), t_real].
    # t_real = real query tokens: the step's tokens occupy positions
    # [length - t_real, length); bucket-padding rows (qt >= t_real) wrote
    # to dropped slots and their outputs are sliced away by the caller.
    # TRACED (not static) so varying real token counts inside one pow2
    # bucket share a compile.
    *refs,
    scale: float,
    page_size: int,
    n_pages: int,
    hkv: int,
    g: int,
    t_q: int,  # query-token BUCKET (may be padded past the real count)
    has_tree: bool,
):
    """T>1 variant of _kernel: each grid step attends ALL T query tokens'
    heads (a [T*H, hd] block) against one K/V page. Covers the two T>1 hot
    paths the dense gather served before (round-4 verdict #5):

    - plain causal chunks: query token t sits at position start+t
      (start = length - T); key visible iff pos <= start+t (and inside the
      per-query sliding window when one is set)
    - tree-verify steps (has_tree): the T new tokens' mutual visibility
      comes from the [T, T] tree mask; the committed prefix (pos < start)
      is fully visible to every tree token (reference backend.py:596-652
      tree masks — here streamed per page instead of materializing
      [B, H, T, S] logits over a gathered context)

    The tree lookup tm[t, pos-start] is expressed as two small one-hot
    matmuls (tm @ sel, then query-row expansion) because Mosaic has no
    arbitrary 2D gather; both contract tiny [T, .] operands on the MXU.
    """
    if has_tree:
        tm_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        tm_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)
    h = hkv * g
    rows = page_size * hkv  # key rows per page
    rq = t_q * h  # query rows

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lens_ref[b]
    win = meta_ref[0]
    t_real = meta_ref[1]
    start = length - t_real
    rk = jax.lax.broadcasted_iota(jnp.int32, (rq, rows), 1)
    rqi = jax.lax.broadcasted_iota(jnp.int32, (rq, rows), 0)
    pos = j * page_size + rk // hkv  # key position
    qh = rqi % h
    qt = rqi // h  # query token index
    own = (rk % hkv) == (qh // g)
    # earliest position ANY query can see (window applies per query; the
    # page-skip bound uses the earliest query t=0)
    low0 = jnp.where(win > 0, jnp.maximum(start + 1 - win, 0), 0)
    page_live = (j * page_size < length) & ((j + 1) * page_size > low0)

    @pl.when(page_live)
    def _update():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rq, rows]
        valid = pos < length
        if tm_ref is None:
            mask = own & valid & (pos <= start + qt) & (qt < t_real)
            mask &= (win <= 0) | (pos > start + qt - win)
        else:
            tm = tm_ref[...].astype(jnp.float32)  # [t_q, t_q]
            ti = jax.lax.broadcasted_iota(jnp.int32, (t_q, rows), 0)
            posk = (
                j * page_size
                + jax.lax.broadcasted_iota(jnp.int32, (t_q, rows), 1) // hkv
            )
            sel = (posk == start + ti).astype(jnp.float32)  # [t_q, rows]
            tree_vis = jax.lax.dot_general(
                tm, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [t_q, rows]
            oh = (
                jax.lax.broadcasted_iota(jnp.int32, (rq, t_q), 0) // h
                == jax.lax.broadcasted_iota(jnp.int32, (rq, t_q), 1)
            ).astype(jnp.float32)
            tree_rows = jax.lax.dot_general(
                oh, tree_vis, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rq, rows]
            mask = own & valid & ((pos < start) | (tree_rows > 0.5))
        logits = jnp.where(mask, logits, NEG)
        m = m_scr[...]
        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new) * mask.astype(jnp.float32)
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + p.sum(axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "scale", "interpret", "has_tree"),
)
def paged_chunk_attention(
    q: jax.Array,  # [B, T, H, hd] — T new tokens per sequence (T may be a
    # padded bucket; t_real marks the real count)
    k_slab: jax.Array,  # [S_tot, Hkv, hd] — the paged arena, one layer
    v_slab: jax.Array,
    page_table: jax.Array,  # [B, NP] i32
    lens: jax.Array,  # [B] i32 (INCLUDING the t_real new tokens)
    page_size: int,
    tree_mask: jax.Array | None = None,  # [B, T, T] bool (has_tree)
    scale: float | None = None,
    interpret: bool = False,
    window=0,  # traced i32 scalar; 0 = full (tree steps gate window off
    # host-side: depth-positioned tree tokens + window stay on the dense
    # path)
    has_tree: bool = False,
    t_real=None,  # real (unpadded) query tokens; None = T. TRACED so real
    # counts inside one pow2 bucket share a compile.
) -> jax.Array:  # [B, T, H, hd]
    """Paged attention for T>1 steps (tree verify, short multi-token
    chunks): one HBM pass over the context pages instead of the dense
    path's gather-then-attend two passes. VMEM budget: caller gates on
    T*H rows (executor allows <= 2048)."""
    b, t_q, h, hd = q.shape
    s_tot, hkv = k_slab.shape[0], k_slab.shape[1]
    if h % hkv:
        raise ValueError(f"H={h} must be a multiple of Hkv={hkv}")
    if s_tot % page_size:
        raise ValueError(f"arena slots {s_tot} % page_size {page_size}")
    g = h // hkv
    n_pages = page_table.shape[1]
    if scale is None:
        scale = hd**-0.5
    if t_real is None:
        t_real = t_q
    rows = page_size * hkv
    rq = t_q * h

    kp = k_slab.reshape(-1, rows, hd)
    vp = v_slab.reshape(-1, rows, hd)
    q2 = q.reshape(b, rq, hd)

    def kv_index(bi, j, pt, ln, mt):
        # page-skip clamp for the windowed-chunk case: the earliest page
        # any query needs starts at max(start + 1 - win, 0)
        first = jnp.where(
            mt[0] > 0,
            jnp.maximum(ln[bi] - mt[1] + 1 - mt[0], 0) // page_size,
            0,
        )
        return (pt[bi, jnp.maximum(j, first)], 0, 0)

    def q_index(bi, j, pt, ln, mt):
        return (bi, 0, 0)

    in_specs = [
        pl.BlockSpec((None, rq, hd), q_index),
        pl.BlockSpec((None, rows, hd), kv_index),
        pl.BlockSpec((None, rows, hd), kv_index),
    ]
    args = [q2, kp, vp]
    if has_tree:
        assert tree_mask is not None
        in_specs.insert(0, pl.BlockSpec((None, t_q, t_q), q_index))
        args.insert(0, tree_mask.astype(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, rq, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((rq, 1), jnp.float32),
            pltpu.VMEM((rq, 1), jnp.float32),
            pltpu.VMEM((rq, hd), jnp.float32),
        ],
    )
    meta_arr = jnp.stack(
        [
            jnp.asarray(window, jnp.int32).reshape(()),
            jnp.asarray(t_real, jnp.int32).reshape(()),
        ]
    )
    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel, scale=scale, page_size=page_size,
            n_pages=n_pages, hkv=hkv, g=g, t_q=t_q, has_tree=has_tree,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rq, hd), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lens.astype(jnp.int32), meta_arr,
        *args,
    )
    return out.reshape(b, t_q, h, hd)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "scale", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,  # [B, H, hd] — one decode token per sequence
    k_slab: jax.Array,  # [S_tot, Hkv, hd] — the paged arena, one layer
    v_slab: jax.Array,
    page_table: jax.Array,  # [B, NP] i32 physical page ids (padding = 0)
    lens: jax.Array,  # [B] i32 context lengths (incl. this token)
    page_size: int,
    scale: float | None = None,
    interpret: bool = False,
    window=0,  # traced i32 scalar; 0 = full attention (per-layer in scan)
) -> jax.Array:  # [B, H, hd]
    b, h, hd = q.shape
    s_tot, hkv = k_slab.shape[0], k_slab.shape[1]
    if h % hkv:
        raise ValueError(f"H={h} must be a multiple of Hkv={hkv}")
    if s_tot % page_size:
        raise ValueError(f"arena slots {s_tot} % page_size {page_size}")
    g = h // hkv
    n_pages = page_table.shape[1]
    if scale is None:
        scale = hd**-0.5

    # arena as pages with heads folded into rows:
    # [n_phys, page_size * Hkv, hd] (free reshape of the contiguous slab)
    kp = k_slab.reshape(-1, page_size * hkv, hd)
    vp = v_slab.reshape(-1, page_size * hkv, hd)

    kv_index = _make_kv_index(page_size)

    grid = (b, n_pages)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, h, hd), lambda bi, j, pt, ln, wn: (bi, 0, 0)),
            pl.BlockSpec((None, page_size * hkv, hd), kv_index),
            pl.BlockSpec((None, page_size * hkv, hd), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (None, h, hd), lambda bi, j, pt, ln, wn: (bi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, page_size=page_size, n_pages=n_pages,
            hkv=hkv, g=g,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lens.astype(jnp.int32), win_arr,
        q, kp, vp,
    )
    return out


def _ragged_kernel(
    pt_ref,  # [B, NP] i32 scalar prefetch: logical page j of seq b
    lens_ref,  # [B] i32 scalar prefetch (lens INCLUDE each seq's new tokens)
    win_ref,  # [1] i32 scalar prefetch: sliding window (0 = full attention)
    *refs,  # [nt_ref (has_tree)], [tree_ref (has_tree)], seq_ref, pos_ref,
    # q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr — see below
    scale: float,
    page_size: int,
    n_pages: int,
    n_seqs: int,
    hkv: int,
    g: int,
    has_tree: bool = False,
    t_max: int = 0,
):
    """Ragged mixed-batch variant of _chunk_kernel: ONE launch covers every
    member of a mixed group (N single-token decode rows + one multi-token
    prefill-chunk row-group). The grid walks (sequence, page); every grid
    step attends ALL rq query rows against sequence b's page j and masks
    rows owned by a different sequence (their online-softmax state passes
    through untouched: p = 0, corr = 1, exactly the masked-page contract of
    _online_softmax_body). Ownership and causality are per ROW — seq_ref /
    pos_ref replace _chunk_kernel's block-uniform (length, t_real) — so
    T=1 and T=chunk members coexist in one [rq, hd] block.

    Scratch persists across the WHOLE grid (init at the first step,
    finalize at the last), not per sequence: that is what lets one q block
    serve B sequences. The x B masked FLOPs are the price of fusing the
    dispatches; the HBM bytes stay one pass over every member's pages —
    the same bytes B separate kernel calls would read. No windowed
    page-skip here (the skip bound is per row, not per block); dead pages
    still predicate off their compute via page_live.

    has_tree switches the causal term into ragged TREE-verify semantics:
    nt_ref[b] is sequence b's in-step (speculative) token count — its last
    nt storage slots hold this step's linearized tree — committed keys
    (pos < length - nt) stay fully visible, and tree_ref[i, m] says whether
    query row i may attend the m-th in-step slot of its own sequence.
    Mosaic has no arbitrary 2D gather, so the per-key lookup rides the
    one-hot matmul trick from _chunk_kernel: sel one-hots each key column
    to its in-step index, tree_vis = tree_ref @ sel."""
    if has_tree:
        nt_ref, tree_ref = refs[0], refs[1]
        refs = refs[2:]
    else:
        nt_ref = tree_ref = None
    (
        seq_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    ) = refs
    b = pl.program_id(0)
    j = pl.program_id(1)
    h = hkv * g
    rows = page_size * hkv
    rq = q_ref.shape[0]

    @pl.when((b == 0) & (j == 0))
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lens_ref[b]
    win = win_ref[0]
    rk = jax.lax.broadcasted_iota(jnp.int32, (rq, rows), 1)
    rqi = jax.lax.broadcasted_iota(jnp.int32, (rq, rows), 0)
    pos = j * page_size + rk // hkv  # key position
    own = (rk % hkv) == ((rqi % h) // g)
    seq = seq_ref[...]  # [rq, 1] — broadcasts over key rows
    qpos = pos_ref[...]
    page_live = j * page_size < length

    @pl.when(page_live)
    def _update():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rq, rows]
        if has_tree:
            ss = length - nt_ref[b]  # first in-step storage slot of seq b
            tm = tree_ref[...].astype(jnp.float32)  # [rq, t_max]
            ti = jax.lax.broadcasted_iota(jnp.int32, (t_max, rows), 0)
            posk = (
                j * page_size
                + jax.lax.broadcasted_iota(jnp.int32, (t_max, rows), 1)
                // hkv
            )
            sel = (posk == ss + ti).astype(jnp.float32)  # [t_max, rows]
            tree_vis = jax.lax.dot_general(
                tm, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rq, rows]
            mask = own & (pos < length) & (seq == b) & (
                (pos < ss) | (tree_vis > 0.5)
            )
        else:
            mask = own & (pos < length) & (seq == b) & (pos <= qpos)
            mask &= (win <= 0) | (pos > qpos - win)
        logits = jnp.where(mask, logits, NEG)
        m = m_scr[...]
        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new) * mask.astype(jnp.float32)
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + p.sum(axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when((b == n_seqs - 1) & (j == n_pages - 1))
    def _finalize():
        # rows owned by no live sequence (bucket padding: seq >= B) never
        # accumulate and divide by eps into zeros, dropped by the caller
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "scale", "interpret", "has_tree"),
)
def paged_ragged_attention(
    q: jax.Array,  # [R, H, hd] — ragged token rows across ALL members
    k_slab: jax.Array,  # [S_tot, Hkv, hd] — the paged arena, one layer
    v_slab: jax.Array,
    page_table: jax.Array,  # [B, NP] i32 physical page ids (padding = 0)
    lens: jax.Array,  # [B] i32 context lengths (incl. each seq's new tokens)
    q_seq: jax.Array,  # [R] i32 owning sequence per token (>= B = padding)
    q_pos: jax.Array,  # [R] i32 context position per token
    page_size: int,
    scale: float | None = None,
    interpret: bool = False,
    window=0,  # traced i32 scalar; 0 = full attention (per-layer in scan)
    nt: jax.Array | None = None,  # [B] i32 in-step token count (has_tree)
    tree_rows: jax.Array | None = None,  # [R, t_max] in-step visibility
    has_tree: bool = False,
) -> jax.Array:  # [R, H, hd]
    """Paged attention over a ragged mixed batch: R tokens spread unevenly
    across B sequences (decode members contribute 1 row, the prefill-chunk
    member contributes its chunk), all in ONE grid launch. Token row i
    belongs to sequence q_seq[i] at context position q_pos[i]; padding rows
    (q_seq >= B) emit zeros. has_tree switches into the ragged TREE-verify
    variant: nt rides as a fourth scalar prefetch and tree_rows (row-major
    in-step visibility, head-expanded here) as an extra VMEM input; the
    window must be 0 (tree groups gate windowed models off host-side).
    VMEM budget: caller gates on R*H rows (the executor allows <= 2048,
    mirroring paged_chunk_attention)."""
    r, h, hd = q.shape
    s_tot, hkv = k_slab.shape[0], k_slab.shape[1]
    if h % hkv:
        raise ValueError(f"H={h} must be a multiple of Hkv={hkv}")
    if s_tot % page_size:
        raise ValueError(f"arena slots {s_tot} % page_size {page_size}")
    g = h // hkv
    b = page_table.shape[0]
    n_pages = page_table.shape[1]
    if scale is None:
        scale = hd**-0.5
    rows = page_size * hkv
    rq = r * h

    kp = k_slab.reshape(-1, rows, hd)
    vp = v_slab.reshape(-1, rows, hd)
    q2 = q.reshape(rq, hd)
    # per-ROW ownership/position: each token's values repeated per head
    seq_rows = jnp.repeat(q_seq.astype(jnp.int32), h).reshape(rq, 1)
    pos_rows = jnp.repeat(q_pos.astype(jnp.int32), h).reshape(rq, 1)

    # index-map arity follows num_scalar_prefetch (3, +1 for the tree
    # variant's nt), so take the prefetch refs variadically
    def kv_index(bi, j, pt, ln, wn, *rest):
        return (pt[bi, j], 0, 0)

    def const_index(bi, j, pt, ln, wn, *rest):
        return (0, 0)

    t_max = tree_rows.shape[1] if has_tree else 0
    in_specs = [
        pl.BlockSpec((rq, 1), const_index),
        pl.BlockSpec((rq, 1), const_index),
        pl.BlockSpec((rq, hd), const_index),
        pl.BlockSpec((None, rows, hd), kv_index),
        pl.BlockSpec((None, rows, hd), kv_index),
    ]
    prefetch = [
        page_table.astype(jnp.int32), lens.astype(jnp.int32),
        jnp.asarray(window, jnp.int32).reshape(1),
    ]
    args = [seq_rows, pos_rows, q2, kp, vp]
    if has_tree:
        assert nt is not None and tree_rows is not None
        prefetch.append(nt.astype(jnp.int32))
        # per-ROW visibility: each token's tree row repeated per head,
        # mirroring seq_rows/pos_rows
        tree_rq = jnp.repeat(
            tree_rows.astype(jnp.float32), h, axis=0
        ).reshape(rq, t_max)
        in_specs.insert(0, pl.BlockSpec((rq, t_max), const_index))
        args.insert(0, tree_rq)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 + int(has_tree),
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((rq, hd), const_index),
        scratch_shapes=[
            pltpu.VMEM((rq, 1), jnp.float32),
            pltpu.VMEM((rq, 1), jnp.float32),
            pltpu.VMEM((rq, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel, scale=scale, page_size=page_size,
            n_pages=n_pages, n_seqs=b, hkv=hkv, g=g,
            has_tree=has_tree, t_max=t_max,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rq, hd), q.dtype),
        interpret=interpret,
    )(*prefetch, *args)
    return out.reshape(r, h, hd)
