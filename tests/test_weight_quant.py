"""Weight-only quantization: roundtrip bounds + span-step closeness.

The weight half of the reference's compression lever
(/root/reference/src/bloombee/flexgen_utils/compression.py:22-210)."""

import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from bloombee_tpu.models.wquant import (
    QuantWeight,
    dequantize_weight,
    params_nbytes,
    quantize_span_params,
    quantize_weight,
)


@pytest.mark.parametrize("bits,tol", [(8, 0.012), (4, 0.09)])
def test_roundtrip_error_bounds(bits, tol):
    rng = np.random.default_rng(0)
    w = rng.standard_normal((256, 192)).astype(np.float32)
    qw = quantize_weight(jnp.asarray(w), bits=bits)
    back = np.asarray(dequantize_weight(qw, jnp.float32))
    # error relative to each column's max magnitude
    err = np.abs(back - w).max(axis=0) / np.abs(w).max(axis=0)
    assert err.max() < tol, err.max()


def test_quantize_span_params_selective_and_smaller():
    rng = np.random.default_rng(1)
    stacked = {
        "q_proj": jnp.asarray(rng.standard_normal((2, 64, 64), np.float32)),
        "input_layernorm": jnp.ones((2, 64), jnp.float32),
        "q_bias": jnp.zeros((2, 64), jnp.float32),
    }
    before = params_nbytes(stacked)
    q8 = quantize_span_params(stacked, 8)
    assert isinstance(q8["q_proj"], QuantWeight)
    assert q8["input_layernorm"] is stacked["input_layernorm"]
    assert q8["q_bias"] is stacked["q_bias"]
    assert params_nbytes(q8) < before / 2.5  # int8 + f32 scales
    q4 = quantize_span_params(stacked, 4)
    assert params_nbytes(q4) < params_nbytes(q8)


# bounds are bits- and phase-aware: a single decode token has far fewer
# activations than a 9-token prefill, so round-to-nearest noise averages
# out less and its cosine floor must sit lower (measured: 4-bit decode
# bottoms out near 0.94 on this seed across dense/MoE; 8-bit near 0.999)
@pytest.mark.parametrize("bits,min_cos,min_cos_decode", [
    (8, 0.998, 0.998), (4, 0.96, 0.93),
])
@pytest.mark.parametrize("family_kw", [
    {},  # llama dense MLP
    {"num_experts": 4, "num_experts_per_tok": 2},  # mixtral-style MoE
])
def test_span_decode_quant_weights_close_to_dense(
    family_kw, bits, min_cos, min_cos_decode
):
    """A full paged span step with int8/int4 weights tracks the dense step
    to quantization tolerance, through prefill and decode (exercises the
    lead-dim stacking, scan slicing, and nibble unpack paths)."""
    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=2, vocab_size=64, **family_kw,
    )
    import jax.random as jr

    layers = []
    for i in range(2):
        p = init_block_params(jr.PRNGKey(i), spec, dtype=jnp.float32)
        if spec.num_experts:
            e, d, m = spec.num_experts, 64, 128
            for k in ("gate_proj", "up_proj", "down_proj"):
                del p[k]
            p["router"] = jr.normal(jr.PRNGKey(10 + i), (d, e)) * 0.1
            p["experts_gate"] = jr.normal(jr.PRNGKey(20 + i), (e, d, m)) * 0.1
            p["experts_up"] = jr.normal(jr.PRNGKey(30 + i), (e, d, m)) * 0.1
            p["experts_down"] = jr.normal(jr.PRNGKey(40 + i), (e, m, d)) * 0.1
        layers.append(p)
    params = stack_params(layers)
    qparams = quantize_span_params(params, bits)
    rng = np.random.default_rng(2)
    prefill = rng.standard_normal((2, 9, 64)).astype(np.float32) * 0.3
    step = rng.standard_normal((2, 1, 64)).astype(np.float32) * 0.3

    async def run(p):
        manager = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=16, dtype=jnp.float32,
        )
        ex = SpanExecutor(p, spec, manager, compute_dtype=jnp.float32)
        async with manager.allocate(2, 16) as handle:
            out1 = ex.prefill(handle, prefill)
            out2 = ex.decode(handle, step)
        return out1, out2

    dense1, dense2 = asyncio.run(run(params))
    q1, q2 = asyncio.run(run(qparams))

    # round-to-nearest quant noise compounds across layers; cosine
    # similarity of the span output is the meaningful closeness metric
    def cos(a, b):
        a, b = np.ravel(a).astype(np.float64), np.ravel(b).astype(np.float64)
        return a @ b / (np.linalg.norm(a) * np.linalg.norm(b))

    assert cos(q1, dense1) > min_cos, cos(q1, dense1)
    assert cos(q2, dense2) > min_cos_decode, cos(q2, dense2)
    # and it must actually be quantized, not silently dense
    assert isinstance(qparams["q_proj"], QuantWeight)
