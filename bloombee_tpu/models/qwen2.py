"""Qwen2/Qwen2.5 family: Llama structure + biased q/k/v projections.

The reference serves Qwen-family checkpoints through HF wrappers; here it is
the llama weight layout plus the attention biases Qwen2 adds (layer_body's
projection helper already applies `{q,k,v}_bias` when present).
"""

from __future__ import annotations

from typing import Any

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.llama.block import (
    HF_BLOCK_KEYS,
    convert_hf_block_params,
)
from bloombee_tpu.models.spec import ModelSpec


def qwen2_spec_from_hf(config: Any) -> ModelSpec:
    if getattr(config, "use_sliding_window", False):
        # released Qwen2/2.5 checkpoints ship use_sliding_window=false; the
        # partial-depth SWA variant (max_window_layers) is not mapped yet
        raise NotImplementedError(
            "qwen2 with use_sliding_window=true is not supported yet"
        )
    return ModelSpec(
        family="qwen2",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=getattr(config, "head_dim", None)
        or config.hidden_size // config.num_attention_heads,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 1_000_000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    prefix = f"model.layers.{layer_idx}"
    tensors = {k: reader.tensor(f"{prefix}.{k}") for k in HF_BLOCK_KEYS}
    params = convert_hf_block_params(tensors, dtype=dtype)
    for proj in ("q", "k", "v"):
        name = f"{prefix}.self_attn.{proj}_proj.bias"
        if reader.has(name):
            params[f"{proj}_bias"] = _t(reader, name, dtype)
    return params


register_family(
    Family("qwen2", qwen2_spec_from_hf, HF_BLOCK_KEYS, loader=_load_block)
)
