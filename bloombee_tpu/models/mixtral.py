"""Mixtral family: Llama-style attention + sparse MoE MLP.

Reference: /root/reference/src/bloombee/models/mixtral/ runs all experts
densely inside one HF block with no expert parallelism; here experts are
stacked tensors (ops/moe.py) and shard over the mesh in the SPMD path —
an improvement the reference explicitly lacks (SURVEY.md section 2.8).
"""

from __future__ import annotations

from typing import Any


from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec


def mixtral_spec_from_hf(config: Any) -> ModelSpec:
    return ModelSpec(
        family="mixtral",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=getattr(config, "head_dim", None)
        or config.hidden_size // config.num_attention_heads,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 1000000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        num_experts=config.num_local_experts,
        num_experts_per_tok=config.num_experts_per_tok,
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    p = f"model.layers.{layer_idx}"
    params = {
        "input_layernorm": _t(reader, f"{p}.input_layernorm.weight", dtype),
        "post_attention_layernorm": _t(
            reader, f"{p}.post_attention_layernorm.weight", dtype
        ),
    }
    for proj in ("q", "k", "v", "o"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.self_attn.{proj}_proj.weight", dtype
        ).T
    params["router"] = _t(
        reader, f"{p}.block_sparse_moe.gate.weight", dtype
    ).T  # [D, E]
    from bloombee_tpu.models.checkpoint import stack_expert_weights

    # mixtral names: w1 = gate, w3 = up, w2 = down
    params.update(
        stack_expert_weights(
            reader, f"{p}.block_sparse_moe.experts.{{}}", "w1", "w3", "w2",
            params["router"].shape[1], dtype,
        )
    )
    return params


register_family(Family("mixtral", mixtral_spec_from_hf, loader=_load_block))
