"""Core numeric ops: norms, rotary embeddings, attention, MLP.

These replace the reference's TorchDevice kernel collection
(/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:285-1081 —
mha_llama/mha_gen_llama/mlp_llama/rms_norm and rotary helpers). Here each op is a
pure jax function; XLA fuses elementwise work into the surrounding matmuls, so the
mha/mha_gen x {gpu,cpu,mixed,compressed} variant matrix collapses into one
implementation family.
"""

from bloombee_tpu.ops.norms import rms_norm
from bloombee_tpu.ops.rotary import apply_rotary, rotary_cos_sin
from bloombee_tpu.ops.attention import masked_attention, repeat_kv
from bloombee_tpu.ops.mlp import silu_mlp

__all__ = [
    "rms_norm",
    "apply_rotary",
    "rotary_cos_sin",
    "masked_attention",
    "repeat_kv",
    "silu_mlp",
]
