"""bbtpu-lint rules BB001–BB013.

Each rule encodes one invariant this codebase has already been burned by
(see ARCHITECTURE.md "Invariants"). Rules are plugin classes over the
shared SourceFile from core.py: per-file `visit_file` plus a cross-file
`finalize` for rules that correlate a declaration in one file with its
surfacing in another (BB006) or need nothing global (most). Rules that
define `prepare(files, graph)` additionally get the module-level call
graph (analysis/callgraph.py) before the per-file pass — BB002/BB003/
BB009 use it to follow lock effects across call edges and print the
full call chain in the finding.

Rule-authoring contract: a rule must be cheap (pure ast walk), must
build findings via ``sf.finding(...)`` so `# bbtpu: noqa[...]` works,
and must prefer missing a contorted true positive over spamming false
positives — the gate is only useful while `scripts/analyze.sh` exits 0
on a healthy tree.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from bloombee_tpu.analysis import lock_hierarchy
from bloombee_tpu.analysis.callgraph import body_walk
from bloombee_tpu.analysis.core import Finding, SourceFile

_STRINGS_RE = re.compile(r"'[^']*'|\"[^\"]*\"")


def _call_name(node: ast.Call) -> str:
    """Trailing name of the called thing: `a.b.write_slots(...)` ->
    'write_slots', `rollback(...)` -> 'rollback'."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _mentions_lock(expr: ast.AST) -> bool:
    """'lock' appears in the expression's code, not inside a string
    literal (`open(".evict.lock")` is a file, not a mutex)."""
    text = _STRINGS_RE.sub("", _expr_text(expr))
    return "lock" in text.lower()


def _is_locked_decorated(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return any("_locked" in _expr_text(d) for d in fn.decorator_list)


class Rule:
    code = "BB000"
    name = "base"
    summary = ""

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        return []

    def finalize(self) -> list[Finding]:
        return []


class SpeculativeWriteRule(Rule):
    """BB001: a speculative KV mutation must be dominated by a try whose
    handlers/finally reach rollback/truncate_speculative.

    Motivated by PR 8: a failed mixed dispatch that plain-rollback'd the
    fused handle destroyed prefill chunks committed by EARLIER chunks —
    the fix (truncate_speculative) only exists because someone noticed.
    Sites that deliberately delegate recovery to their caller (the
    stream driver owns the handle's lifecycle) carry
    `# bbtpu: noqa[BB001]` with a comment naming the owner.
    """

    code = "BB001"
    name = "speculative-write-unprotected"
    summary = (
        "speculative KV mutation not dominated by a try reaching "
        "rollback/truncate_speculative"
    )

    # These mutate KV speculatively no matter how they're called.
    ALWAYS = {"append_speculative", "decode_group", "mixed_group"}
    # These are speculative only when explicitly called commit=False
    # (a literal False keyword; `commit=commit` pass-through is the
    # callee's own contract and stays quiet).
    WHEN_COMMIT_FALSE = {
        "write_slots",
        "write_slots_ragged",
        "assign_write_slots",
        "prefill",
        "prefill_chunk",
        "prefill_chunked",
        "decode",
        "decode_n",
        "step",
        "_step",
        "_step_once",
    }
    RECOVERY = {
        "commit",
        "rollback",
        "truncate_speculative",
        "rollback_if_valid",
        "_rollback_if_valid",
        "abort_chunked_prefill",
        "_abort_chunked_prefill",
    }

    def _is_speculative(self, node: ast.Call) -> bool:
        name = _call_name(node)
        if name in self.ALWAYS:
            return True
        if name not in self.WHEN_COMMIT_FALSE:
            return False
        return any(
            kw.arg == "commit"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in node.keywords
        )

    def _has_recovery(self, stmts: list[ast.stmt]) -> bool:
        for stmt in stmts:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) and (
                    _call_name(n) in self.RECOVERY
                ):
                    return True
        return False

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        # id()-sets of every node inside a try body whose failure path
        # (handlers or finally) reaches a recovery call
        guarded: list[set[int]] = []
        for t in ast.walk(sf.tree):
            if not isinstance(t, ast.Try):
                continue
            recovery_stmts: list[ast.stmt] = list(t.finalbody)
            for h in t.handlers:
                recovery_stmts.extend(h.body)
            if not self._has_recovery(recovery_stmts):
                continue
            guarded.append(
                {
                    id(x)
                    for stmt in t.body
                    for x in ast.walk(stmt)
                }
            )
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_speculative(node):
                continue
            if any(id(node) in g for g in guarded):
                continue
            f = sf.finding(
                self.code,
                node,
                f"speculative KV write `{_call_name(node)}(...)` is not "
                "dominated by a try whose handlers reach "
                "rollback/truncate_speculative; wrap it, or mark the "
                "recovery owner with `# bbtpu: noqa[BB001]`",
            )
            if f:
                out.append(f)
        return out


class BlockingUnderLockRule(Rule):
    """BB002: no blocking call while a threading lock is held — now
    TRANSITIVE across call edges.

    CacheManager serializes on one RLock (`@_locked`); a recv/sleep/
    future-result/device-sync inside it stalls every session on the
    server, which is exactly the head-of-line blocking PR 5/8 spent two
    PRs removing from the dispatch path. v2: `with lock: flush()` where
    flush() sleeps three helpers down is the same bug, so any resolved
    call under the lock whose callee transitively reaches a blocking
    site is flagged with the full call chain. asyncio locks are out of
    scope here (they don't pin a thread) — BB009 owns the event loop.
    """

    code = "BB002"
    name = "blocking-call-under-lock"
    summary = "blocking call while a threading lock is held"

    BLOCKING_ATTRS = {
        "sleep",
        "recv",
        "result",
        "block_until_ready",
        "resolve",
    }

    def __init__(self):
        self._graph = None
        self._chains: dict[str, tuple[str, ...]] = {}
        self._site: dict[str, str] = {}  # qname -> its blocking callee

    def _is_blocking(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in self.BLOCKING_ATTRS:
                return True
            # device dispatch through the executor is a synchronous
            # multi-ms device round-trip
            if "executor" in _STRINGS_RE.sub("", _expr_text(f.value)):
                return True
        return False

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._graph = graph
        for q, fi in graph.functions.items():
            for n in body_walk(fi.node):
                if isinstance(n, ast.Call) and self._is_blocking(n):
                    self._site[q] = _expr_text(n.func)
                    break
        self._chains = graph.reach(set(self._site))

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        graph = self._graph

        def walk(node: ast.AST, depth: int, cls, fname: str) -> None:
            if isinstance(node, ast.ClassDef):
                for child in ast.iter_child_nodes(node):
                    walk(child, depth, node.name, fname)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def's body doesn't run under the outer lock
                inner = 1 if _is_locked_decorated(node) else 0
                label = f"{cls}.{node.name}" if cls else node.name
                for child in ast.iter_child_nodes(node):
                    walk(child, inner, cls, label)
                return
            d = depth
            if isinstance(node, ast.With):  # sync only, not AsyncWith
                if any(
                    _mentions_lock(item.context_expr)
                    for item in node.items
                ):
                    d = depth + 1
            if depth > 0 and isinstance(node, ast.Call):
                if self._is_blocking(node):
                    f = sf.finding(
                        self.code,
                        node,
                        f"blocking call `{_expr_text(node.func)}(...)` "
                        "while a threading lock is held stalls every "
                        "thread contending for it; move it outside the "
                        "lock",
                    )
                    if f:
                        out.append(f)
                elif graph is not None:
                    q = graph.resolve(sf.path, cls, node)
                    chain = self._chains.get(q) if q else None
                    if chain:
                        names = tuple(graph.display(x) for x in chain)
                        if fname:
                            names = (fname,) + names
                        f = sf.finding(
                            self.code,
                            node,
                            f"call `{_expr_text(node.func)}(...)` while "
                            "a threading lock is held reaches blocking "
                            f"`{self._site[chain[-1]]}(...)` via "
                            f"{' -> '.join(names)}; move the blocking "
                            "work outside the lock",
                            chain=names,
                        )
                        if f:
                            out.append(f)
            for child in ast.iter_child_nodes(node):
                walk(child, d, cls, fname)

        walk(sf.tree, 0, None, "")
        return out


class LockOrderRule(Rule):
    """BB003: locks must be acquired in the declared hierarchy
    (analysis/lock_hierarchy.py) — now covering every package lock
    (thread AND asyncio) and TRANSITIVE across call edges.

    Acquiring a lower-level lock while holding a higher-level one is the
    classic ABBA deadlock setup; the levels in lock_hierarchy.HIERARCHY
    match the call direction the code actually uses (replication sweep
    reaches into the peer pool and the wire, manager methods reach into
    the table — never the reverse). v2 also flags a call site under a
    held lock whose callee transitively acquires an out-of-order lock,
    with the full call chain, and resolves simple local aliases
    (`lock = self._locks.setdefault(...)` then `async with lock:`).
    """

    code = "BB003"
    name = "lock-order-violation"
    summary = "lock acquired against the declared hierarchy"

    def __init__(self):
        self._graph = None
        # lock key -> {qname: shortest chain to a direct acquirer}
        self._chains: dict[str, dict[str, tuple[str, ...]]] = {}

    @staticmethod
    def _classify(sf: SourceFile, expr: ast.AST, aliases: dict) -> str | None:
        if isinstance(expr, ast.Name) and expr.id in aliases:
            return aliases[expr.id]
        text = _STRINGS_RE.sub("", _expr_text(expr)).lower()
        return lock_hierarchy.classify(text, sf.path)

    @classmethod
    def _aliases(cls, sf: SourceFile, fn: ast.AST) -> dict[str, str]:
        """name -> lock key for simple local lock aliases inside fn."""
        out: dict[str, str] = {}
        for n in body_walk(fn):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                text = _STRINGS_RE.sub("", _expr_text(n.value)).lower()
                key = lock_hierarchy.classify(text, sf.path)
                if key:
                    out[n.targets[0].id] = key
        return out

    @classmethod
    def _direct_keys(cls, sf: SourceFile, fn: ast.AST) -> set[str]:
        keys: set[str] = set()
        if sf.path.endswith("kv/cache_manager.py") and _is_locked_decorated(
            fn
        ):
            keys.add("kv.cache_manager")
        aliases = cls._aliases(sf, fn)
        for n in body_walk(fn):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    k = cls._classify(sf, item.context_expr, aliases)
                    if k:
                        keys.add(k)
        return keys

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._graph = graph
        direct = {
            q: self._direct_keys(fi.sf, fi.node)
            for q, fi in graph.functions.items()
        }
        all_keys = set().union(*direct.values()) if direct else set()
        self._chains = {
            k: graph.reach({q for q, ks in direct.items() if k in ks})
            for k in sorted(all_keys)
        }

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        graph = self._graph
        in_cm = sf.path.endswith("kv/cache_manager.py")

        def walk(node, held: list[str], cls, fname: str, aliases) -> None:
            if isinstance(node, ast.ClassDef):
                for child in ast.iter_child_nodes(node):
                    walk(child, held, node.name, fname, aliases)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # @_locked methods run with the cache_manager lock held
                inner = (
                    ["kv.cache_manager"]
                    if (in_cm and _is_locked_decorated(node))
                    else []
                )
                label = f"{cls}.{node.name}" if cls else node.name
                fa = self._aliases(sf, node)
                for child in ast.iter_child_nodes(node):
                    walk(child, inner, cls, label, fa)
                return
            h = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    k = self._classify(sf, item.context_expr, aliases)
                    if k is None:
                        continue
                    for prev in h:
                        ok, why = lock_hierarchy.edge_allowed(prev, k)
                        if not ok:
                            f = sf.finding(
                                self.code,
                                node,
                                f"acquires `{k}` while holding `{prev}`: "
                                f"{why} (see analysis/lock_hierarchy.py)",
                            )
                            if f:
                                out.append(f)
                            break
                    h = h + [k]
            elif h and isinstance(node, ast.Call) and graph is not None:
                q = graph.resolve(sf.path, cls, node)
                if q:
                    done = False
                    for k, chains in self._chains.items():
                        if done:
                            break
                        chain = chains.get(q)
                        if not chain:
                            continue
                        for prev in h:
                            ok, why = lock_hierarchy.edge_allowed(prev, k)
                            if ok:
                                continue
                            names = tuple(
                                graph.display(x) for x in chain
                            )
                            if fname:
                                names = (fname,) + names
                            f = sf.finding(
                                self.code,
                                node,
                                f"call `{_expr_text(node.func)}(...)` "
                                f"transitively acquires `{k}` via "
                                f"{' -> '.join(names)} while holding "
                                f"`{prev}`: {why} (see "
                                "analysis/lock_hierarchy.py)",
                                chain=names,
                            )
                            if f:
                                out.append(f)
                            done = True
                            break
            for child in ast.iter_child_nodes(node):
                walk(child, h, cls, fname, aliases)

        walk(sf.tree, [], None, "", {})
        return out


class WireCompatRule(Rule):
    """BB004: a wire dataclass whose `from_wire` splats the wire dict
    into the constructor must (a) filter unknown keys through
    dataclasses.fields and (b) default every field.

    PR 6's compat story in one rule: (a) lets an OLD server accept a
    NEW peer's dict (unknown fields dropped), (b) lets a NEW server
    accept an OLD peer's dict (missing fields defaulted). from_wire
    bodies that construct field-by-field (TensorMeta) opt out of the
    splat pattern and are trusted to handle versioning manually.
    """

    code = "BB004"
    name = "wire-field-compat"
    summary = "wire dataclass field without from_wire filter or default"

    def _is_dataclass(self, cls: ast.ClassDef) -> bool:
        for d in cls.decorator_list:
            if "dataclass" in _expr_text(d):
                return True
        return False

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not self._is_dataclass(cls):
                continue
            fw = next(
                (
                    n
                    for n in cls.body
                    if isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                    and n.name == "from_wire"
                ),
                None,
            )
            if fw is None:
                continue
            splat = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "cls"
                and any(kw.arg is None for kw in n.keywords)
                for n in ast.walk(fw)
            )
            if not splat:
                continue
            filtered = any(
                isinstance(n, ast.Call) and _call_name(n) == "fields"
                for n in ast.walk(fw)
            )
            if not filtered:
                f = sf.finding(
                    self.code,
                    fw,
                    f"{cls.name}.from_wire splats the wire dict into "
                    "cls(**...) without a dataclasses.fields filter; "
                    "a newer peer's unknown field will crash this "
                    "version",
                )
                if f:
                    out.append(f)
            for stmt in cls.body:
                if (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and stmt.value is None
                    and not stmt.target.id.startswith("_")
                ):
                    f = sf.finding(
                        self.code,
                        stmt,
                        f"wire field {cls.name}.{stmt.target.id} has no "
                        "default; an older peer's dict that lacks it "
                        "will crash from_wire",
                    )
                    if f:
                        out.append(f)
        return out


class EnvRegistryRule(Rule):
    """BB005: every BBTPU_* switch is read through utils/env.get, never
    raw os.environ/getenv.

    The registry is what makes `cli/health --switches` and the README
    table authoritative; a raw read is an undocumented switch with no
    type coercion and no default in one place. Raw WRITES (tests and
    bench save/set/restore) are out of scope.
    """

    code = "BB005"
    name = "env-read-bypasses-registry"
    summary = "raw os.environ/getenv read of a BBTPU_* switch"

    def _bbtpu_key(self, node: ast.AST) -> str | None:
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("BBTPU_")
        ):
            return node.value
        return None

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        if sf.path.endswith("utils/env.py"):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            key = None
            if isinstance(node, ast.Call) and node.args:
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "get"
                    and _expr_text(f.value).endswith("environ")
                ):
                    key = self._bbtpu_key(node.args[0])
                elif _call_name(node) == "getenv":
                    key = self._bbtpu_key(node.args[0])
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if _expr_text(node.value).endswith("environ"):
                    key = self._bbtpu_key(node.slice)
            if key is None:
                continue
            f = sf.finding(
                self.code,
                node,
                f"raw environment read of {key} bypasses "
                "utils/env.declare; declare the switch and read it "
                "via env.get",
            )
            if f:
                out.append(f)
        return out


class CounterSurfacingRule(Rule):
    """BB006: a counter incremented in server/kv code must be surfaced —
    its name must appear as a string literal somewhere in the scanned
    tree (rpc_info dict key, health --probe key, stats() dict).

    A counter nobody can read is debugging theater: PR 4/5/8 each
    shipped counters precisely so operators can see replication lag /
    chunking / fusing without log access. Private bookkeeping escapes
    with a leading underscore.
    """

    code = "BB006"
    name = "counter-not-surfaced"
    summary = "server counter never surfaced via rpc_info/health"

    def __init__(self):
        # name -> (SourceFile, node) of the first increment site
        self.counters: dict[str, tuple[SourceFile, ast.AST]] = {}
        self.surfaced: set[str] = set()

    SCOPES = ("/server/", "/kv/", "server/", "kv/")

    def _in_scope(self, path: str) -> bool:
        return "/server/" in path or "/kv/" in path or path.startswith(
            ("server/", "kv/")
        )

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                self.surfaced.add(node.value)
        if self._in_scope(sf.path):
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.AugAssign)
                    and isinstance(node.target, ast.Attribute)
                    and isinstance(node.target.value, ast.Name)
                    and node.target.value.id == "self"
                    and not node.target.attr.startswith("_")
                ):
                    self.counters.setdefault(
                        node.target.attr, (sf, node)
                    )
        return []

    def finalize(self) -> list[Finding]:
        out = []
        for name, (sf, node) in sorted(self.counters.items()):
            if name in self.surfaced:
                continue
            f = sf.finding(
                self.code,
                node,
                f"counter `self.{name}` is incremented in server code "
                "but never surfaced (no string literal names it in "
                "rpc_info / health --probe / stats()); surface it or "
                "prefix it with `_`",
            )
            if f:
                out.append(f)
        return out


class ExactTensorCompareRule(Rule):
    """BB007: no exact equality on hidden-state tensors in client/server
    verification paths.

    Honest replicas differ in ulps: float reductions are batch-width
    dependent (a server batching our rows with a stranger's sums in a
    different order), so `lie == truth`-style checks convict honest
    peers — the exact trap the integrity layer's `tensors_close`
    (client/integrity.py) exists to avoid. Byte-exact digests over the
    SAME serialized array (kv/prefix.out_digest) are a different thing
    and stay quiet: the rule only fires on float-compare calls
    (np.array_equal & co.) and on `==`/`!=` where BOTH sides are
    hidden-state expressions. Shape/dtype/index comparisons are excluded
    by token.
    """

    code = "BB007"
    name = "exact-float-tensor-compare"
    summary = "exact equality compare on hidden-state tensors"

    EQ_CALLS = {"array_equal", "array_equiv", "assert_array_equal"}
    HIDDENISH = ("hidden", "activation", "logits")
    # any of these underscore-separated name parts anywhere in the
    # expression means it is NOT a float-tensor payload (geometry,
    # bookkeeping, identifiers). Matched per-part, not per-substring:
    # "hidden" must not be excluded just because it contains "id"
    EXCLUDE = {
        "shape", "dtype", "size", "dim", "dims", "len", "count", "num",
        "idx", "index", "id", "ids", "step", "pos", "digest", "token",
        "tokens",
    }

    def _in_scope(self, path: str) -> bool:
        return (
            "/client/" in path
            or "/server/" in path
            or path.startswith(("client/", "server/"))
        )

    @staticmethod
    def _tokens(node: ast.AST) -> list[str]:
        toks = []
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                toks.append(n.id.lower())
            elif isinstance(n, ast.Attribute):
                toks.append(n.attr.lower())
        return toks

    def _hiddenish(self, node: ast.AST) -> bool:
        toks = self._tokens(node)
        if any(p in self.EXCLUDE for t in toks for p in t.split("_")):
            return False
        for t in toks:
            if any(h in t for h in self.HIDDENISH):
                return True
            # span outputs are conventionally named out / outs / *_out
            if any(p in ("out", "outs", "outputs") for p in t.split("_")):
                return True
        return False

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        if not self._in_scope(sf.path):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            bad = None
            if isinstance(node, ast.Call):
                if _call_name(node) in self.EQ_CALLS and any(
                    self._hiddenish(a) for a in node.args
                ):
                    bad = f"`{_call_name(node)}(...)`"
            elif isinstance(node, ast.Compare):
                if (
                    all(
                        isinstance(op, (ast.Eq, ast.NotEq))
                        for op in node.ops
                    )
                    and self._hiddenish(node.left)
                    and all(
                        self._hiddenish(c) for c in node.comparators
                    )
                ):
                    bad = f"`{_expr_text(node)}`"
            if bad is None:
                continue
            f = sf.finding(
                self.code,
                node,
                f"exact equality {bad} on hidden-state tensors convicts "
                "honest replicas over ulp drift (float reductions are "
                "batch-width dependent); use the dtype-aware "
                "tensors_close (client/integrity.py) instead",
            )
            if f:
                out.append(f)
        return out


class RawClockRule(Rule):
    """BB008: package code must tell time through utils/clock.py, never
    the stdlib directly.

    The deterministic chaos substrate works by swapping the process
    clock (scaled for soak runs, steppable for timing tests): every
    lease expiry, ban probe, quarantine window, keepalive and announce
    period advances on `clock.*`. One raw `time.monotonic()` in a
    timing decision silently splits the codebase into two clock domains
    and the steppable tests hang (virtual time advances, the raw site
    doesn't). Flags calls to ``time()``/``monotonic()``/``sleep()`` on
    any imported alias of the ``time`` module, and ``from time import``
    of those names (they escape as callbacks). ``time.perf_counter()``
    stays legal: duration *measurement* (throughput, codec timing) must
    read real hardware time even under a virtual clock — but it must
    never feed a deadline. Out-of-package harnesses (bench.py, scripts)
    keep real time and are out of scope.
    """

    code = "BB008"
    name = "raw-clock"
    summary = "raw time.time/monotonic/sleep bypasses the virtual clock"

    BANNED = {"time", "monotonic", "sleep"}

    def _in_scope(self, path: str) -> bool:
        p = path.replace("\\", "/")
        if "bloombee_tpu/" not in p and not p.startswith(
            ("client/", "server/", "kv/", "swarm/", "wire/", "utils/",
             "models/", "runtime/", "cli/", "analysis/")
        ):
            return False
        return not p.endswith("utils/clock.py")

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        if not self._in_scope(sf.path):
            return []
        out: list[Finding] = []
        aliases: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time" and node.level == 0:
                    for a in node.names:
                        if a.name in self.BANNED:
                            f = sf.finding(
                                self.code, node,
                                f"`from time import {a.name}` escapes the "
                                "virtual clock as a bare callable; import "
                                "bloombee_tpu.utils.clock and call "
                                f"clock.{'now' if a.name == 'time' else a.name}"
                                "() instead",
                            )
                            if f:
                                out.append(f)
        if not aliases:
            return out
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in aliases
                and fn.attr in self.BANNED
            ):
                repl = "now" if fn.attr == "time" else fn.attr
                f = sf.finding(
                    self.code, node,
                    f"raw `{fn.value.id}.{fn.attr}()` bypasses the virtual "
                    "clock (utils/clock.py): steppable/scaled test clocks "
                    "cannot reach it, so chaos timing tests hang or race; "
                    f"use clock.{repl}() (clock.async_sleep() in "
                    "coroutines; clock.perf_counter() is allowed for pure "
                    "duration measurement)",
                )
                if f:
                    out.append(f)
        return out


class AsyncBlockingRule(Rule):
    """BB009: blocking sync work on the event loop.

    One stalled loop tick delays EVERY session on the server — an
    event-loop stall is a time-between-tokens regression for the whole
    swarm, the exact Orca-metric the batcher exists to protect. Two
    modes on the shared call graph:

    - direct: a blocking sync call (`clock.sleep`, d2h `.resolve()` /
      `block_until_ready`, `open` file I/O, tensor (de)serialization)
      written directly in a coroutine body. Awaited calls are exempt
      (`await clock.async_sleep()` suspends, it doesn't block), and
      callables passed to `to_thread`/`run_in_executor` never look like
      call sites, so thread offload stays quiet by construction.
    - transitive, inside an `async with <lock>` critical section: a
      resolved call whose callee reaches a blocking site through the
      call graph. Under an asyncio lock a stall is a convoy — every
      task queued on the lock serializes behind the blocked tick — so
      the deeper search is worth its false-positive risk there, and
      only there.

    Out-of-package harnesses (bench.py, scripts/) keep their blocking
    I/O and are out of scope, like BB008.
    """

    code = "BB009"
    name = "event-loop-blocking-call"
    summary = "blocking sync call on the event loop / under an asyncio lock"

    BLOCKING_ATTRS = {"sleep", "resolve", "block_until_ready"}
    BLOCKING_NAMES = {"open", "serialize_tensors", "deserialize_tensors"}

    def __init__(self):
        self._graph = None
        self._chains: dict[str, tuple[str, ...]] = {}
        self._site: dict[str, str] = {}

    def _in_scope(self, path: str) -> bool:
        p = path.replace("\\", "/")
        return "bloombee_tpu/" in p or p.startswith(
            ("client/", "server/", "kv/", "swarm/", "wire/", "utils/",
             "models/", "runtime/", "cli/", "analysis/")
        )

    def _is_blocking(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute):
            return (
                f.attr in self.BLOCKING_ATTRS
                or f.attr in self.BLOCKING_NAMES
            )
        if isinstance(f, ast.Name):
            return f.id in self.BLOCKING_NAMES
        return False

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._graph = graph
        for q, fi in graph.functions.items():
            if not self._in_scope(fi.path):
                continue
            nodes = list(body_walk(fi.node))
            awaited = {
                id(n.value)
                for n in nodes
                if isinstance(n, ast.Await)
                and isinstance(n.value, ast.Call)
            }
            for n in nodes:
                if (
                    isinstance(n, ast.Call)
                    and id(n) not in awaited
                    and self._is_blocking(n)
                ):
                    self._site[q] = _expr_text(n.func)
                    break
        self._chains = graph.reach(set(self._site))

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        if not self._in_scope(sf.path):
            return []
        out: list[Finding] = []
        graph = self._graph
        awaited = {
            id(n.value)
            for n in ast.walk(sf.tree)
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)
        }

        def walk(node, cls, fname: str, in_async: bool, alock: int):
            if isinstance(node, ast.ClassDef):
                for child in ast.iter_child_nodes(node):
                    walk(child, node.name, fname, False, 0)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs when called, not here; its body gets
                # its own loop/lock context
                label = f"{cls}.{node.name}" if cls else node.name
                is_async = isinstance(node, ast.AsyncFunctionDef)
                for child in ast.iter_child_nodes(node):
                    walk(child, cls, label, is_async, 0)
                return
            a = alock
            if isinstance(node, ast.AsyncWith):
                if any(
                    _mentions_lock(item.context_expr)
                    for item in node.items
                ):
                    a = alock + 1
            if isinstance(node, ast.Call):
                if (
                    in_async
                    and id(node) not in awaited
                    and self._is_blocking(node)
                ):
                    where = (
                        "inside an `async with` lock critical section"
                        if alock
                        else "in a coroutine on the event loop"
                    )
                    f = sf.finding(
                        self.code,
                        node,
                        "blocking sync call "
                        f"`{_expr_text(node.func)}(...)` {where} stalls "
                        "every task on the loop (a TBT regression for "
                        "every session); await an async variant or move "
                        "it to asyncio.to_thread/run_in_executor",
                    )
                    if f:
                        out.append(f)
                elif alock and in_async and graph is not None:
                    q = graph.resolve(sf.path, cls, node)
                    chain = self._chains.get(q) if q else None
                    if chain:
                        names = tuple(graph.display(x) for x in chain)
                        if fname:
                            names = (fname,) + names
                        f = sf.finding(
                            self.code,
                            node,
                            f"call `{_expr_text(node.func)}(...)` inside "
                            "an `async with` lock critical section "
                            "reaches blocking "
                            f"`{self._site[chain[-1]]}(...)` via "
                            f"{' -> '.join(names)}; the loop stalls with "
                            "the lock held, convoying every task queued "
                            "on it — move the blocking work to a thread "
                            "or out of the critical section",
                            chain=names,
                        )
                        if f:
                            out.append(f)
            for child in ast.iter_child_nodes(node):
                walk(child, cls, fname, in_async, a)

        walk(sf.tree, None, "", False, 0)
        return out


class FireAndForgetTaskRule(Rule):
    """BB010: no fire-and-forget `create_task`/`ensure_future`.

    A task whose handle is discarded loses its exception to the GC's
    "Task exception was never retrieved" black hole — and the task
    itself can be collected mid-flight (asyncio only holds a weak
    reference). The promotion/announce loops died exactly this way
    before the supervisor existed. Only a bare expression statement
    counts: assigning the handle, returning it, passing it to a
    gather/list, or chaining `.add_done_callback(...)` (the rpc._spawn
    pattern) all keep an owner and stay quiet.
    """

    code = "BB010"
    name = "fire-and-forget-task"
    summary = "create_task/ensure_future handle discarded"

    SPAWNERS = {"create_task", "ensure_future"}

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and _call_name(node.value) in self.SPAWNERS
            ):
                f = sf.finding(
                    self.code,
                    node,
                    "task handle discarded: fire-and-forget "
                    f"`{_expr_text(node.value.func)}(...)` loses the "
                    "task's exception and the task itself can be GC'd "
                    "mid-flight; keep the handle and attach "
                    "add_done_callback (see wire/rpc.py _spawn) or "
                    "register it with the supervisor",
                )
                if f:
                    out.append(f)
        return out


# --------------------------------------------------------------------------
# JIT-boundary rules (BB011–BB013). Shared scanner: every jax.jit entry
# point in the tree, with its static (shape-bearing) and donated argument
# names. Two defining idioms are recognized:
#
#   span_step = functools.partial(jax.jit, static_argnames=(...),
#                                 donate_argnames=(...))(span_step_impl)
#   @functools.partial(jax.jit, donate_argnames=(...))
#   def _arena_write_all(arena_k, arena_v, ...): ...
#
# plus plain @jax.jit / name = jax.jit(impl). argnums variants map to
# names through the impl's positional parameter order.


@dataclasses.dataclass
class _JitEntry:
    name: str
    path: str
    params: list[str]  # positional parameter order of the impl
    statics: set[str]
    donated: set[str]


def _str_tuple(node: ast.AST) -> list[str]:
    vals = []
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            vals.append(e.value)
    return vals


def _int_tuple(node: ast.AST) -> list[int]:
    vals = []
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            vals.append(e.value)
    return vals


def _jit_keywords(call: ast.Call) -> dict[str, ast.AST] | None:
    """If `call` is a jax.jit(...) / functools.partial(jax.jit, ...)
    configuration call, its keyword nodes; else None."""
    text = _expr_text(call.func)
    if text.endswith("jit"):
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    if _call_name(call) == "partial" and call.args:
        if _expr_text(call.args[0]).endswith("jit"):
            return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    return None


def _param_names(fn: ast.AST) -> list[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _outermost_functions(tree: ast.AST):
    """Function defs not nested inside another function def: closures
    are analyzed via their enclosing function's walk (they share its
    frame), and walking them twice would duplicate findings."""
    nested: set[int] = set()
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    nested.add(id(sub))
    for fn in ast.walk(tree):
        if (
            isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and id(fn) not in nested
        ):
            yield fn


def scan_jit_entries(files: list[SourceFile]) -> dict[str, _JitEntry]:
    """Name -> entry for every recognized jit entry point. First
    definition wins on a (pathological) name collision."""
    out: dict[str, _JitEntry] = {}

    def add(name, path, params, kws):
        statics = set(_str_tuple(kws.get("static_argnames", ast.Tuple([], None))))
        donated = set(_str_tuple(kws.get("donate_argnames", ast.Tuple([], None))))
        for i in _int_tuple(kws.get("static_argnums", ast.Tuple([], None))):
            if 0 <= i < len(params):
                statics.add(params[i])
        for i in _int_tuple(kws.get("donate_argnums", ast.Tuple([], None))):
            if 0 <= i < len(params):
                donated.add(params[i])
        out.setdefault(
            name, _JitEntry(name, path, params, statics, donated)
        )

    for sf in files:
        defs = {
            n.name: n
            for n in ast.walk(sf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        kws = _jit_keywords(dec)
                        if kws is not None:
                            add(node.name, sf.path, _param_names(node), kws)
                    elif _expr_text(dec).endswith("jit"):
                        add(node.name, sf.path, _param_names(node), {})
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                # name = functools.partial(jax.jit, ...)(impl)  or
                # name = jax.jit(impl, static_argnames=...)
                inner = node.value
                kws = None
                impl = None
                if isinstance(inner.func, ast.Call):
                    kws = _jit_keywords(inner.func)
                    impl = inner.args[0] if inner.args else None
                else:
                    text = _expr_text(inner.func)
                    if text.endswith("jit"):
                        kws = {
                            kw.arg: kw.value
                            for kw in inner.keywords
                            if kw.arg
                        }
                        impl = inner.args[0] if inner.args else None
                if kws is None:
                    continue
                params: list[str] = []
                if isinstance(impl, ast.Name) and impl.id in defs:
                    params = _param_names(defs[impl.id])
                add(node.targets[0].id, sf.path, params, kws)
    return out


class HotPathHostSyncRule(Rule):
    """BB011: no implicit device→host sync reachable from a decode hot
    path.

    The compute queue serializes every session's device work; one
    `.item()` / `float(out)` / `np.asarray(out)` / `block_until_ready`
    inside the dispatch subtree stalls the whole pipeline for a device
    round trip per step — the convoy PR 5/8 removed by making fetch an
    off-queue operation. Hot roots are the group dispatchers and the
    step driver; reachability rides the PR-14 call graph, and each
    finding prints the chain from its root. `float()`/`int()`/`bool()`/
    `np.asarray` only fire on device-ish value names (out/logits/
    dev/...) — host-side numpy bookkeeping (`int(lens.max())`) is not a
    sync. The one deliberate sync (executor.fetch, wire-bound by
    contract) carries an owner noqa.
    """

    code = "BB011"
    name = "hot-path-host-sync"
    summary = "implicit device->host sync reachable from a decode hot path"

    HOT_ROOTS = {
        "decode_group", "mixed_group", "tree_group", "prefill_chunk",
        "_run_step",
    }
    ALWAYS_SYNC_ATTRS = {"item", "block_until_ready", "device_get"}
    CAST_NAMES = {"float", "int", "bool"}
    NP_ALIASES = {"np", "numpy", "onp"}
    DEVICEISH = {"out", "dev", "device", "logits", "toks"}
    # code shipped to another thread is off the compute queue / event
    # loop by construction — the entire point of these wrappers
    OFFLOAD_CALLS = {"to_thread", "run_in_executor"}
    # a name bound from one of these is a HOST value: the d2h round
    # trip already happened, deliberately, at the one chokepoint
    HOST_PRODUCERS = {"to_thread", "run_in_executor", "fetch"}

    def __init__(self):
        self._graph = None
        self._hot: dict[str, tuple[str, ...]] = {}  # qname -> chain

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._graph = graph
        roots = [
            q for q, fi in graph.functions.items()
            if fi.name in self.HOT_ROOTS
        ]
        parent: dict[str, str] = {}
        seen = set(roots)
        queue = list(roots)
        while queue:
            q = queue.pop(0)
            for callee, _ in graph.edges.get(q, ()):
                if callee not in seen:
                    seen.add(callee)
                    parent[callee] = q
                    queue.append(callee)
        for q in seen:
            chain = [q]
            while chain[-1] in parent:
                chain.append(parent[chain[-1]])
            self._hot[q] = tuple(reversed(chain))

    def _deviceish(self, node: ast.AST, host_names: set[str]) -> bool:
        for n in ast.walk(node):
            name = None
            if isinstance(n, ast.Name):
                name = n.id
            elif isinstance(n, ast.Attribute):
                name = n.attr
            if name is None or name in host_names:
                continue
            if any(p in self.DEVICEISH for p in name.lower().split("_")):
                return True
        return False

    @classmethod
    def _host_names(cls, fn: ast.AST) -> set[str]:
        """Names this function declares host-side: parameters annotated
        np.ndarray, and names bound from an offload wrapper or a
        fetch() — the sync already happened where it belongs."""
        out: set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
                ann = _expr_text(p.annotation) if p.annotation else ""
                if "ndarray" in ann:
                    out.add(p.arg)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Assign):
                continue
            v = n.value
            if isinstance(v, ast.Await):
                v = v.value
            if (
                isinstance(v, ast.Call)
                and _call_name(v) in cls.HOST_PRODUCERS
            ):
                for t in n.targets:
                    elts = (
                        t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t]
                    )
                    out.update(
                        e.id for e in elts if isinstance(e, ast.Name)
                    )
        return out

    @classmethod
    def _offloaded_ids(cls, fn: ast.AST) -> set[int]:
        """Ids of nodes inside the argument subtrees of
        asyncio.to_thread / loop.run_in_executor calls: that code runs
        on another thread, off the compute queue."""
        out: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Call)
                and _call_name(n) in cls.OFFLOAD_CALLS
            ):
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    out.update(id(x) for x in ast.walk(a))
        return out

    def _sync_site(
        self, node: ast.Call, host_names: set[str]
    ) -> str | None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in self.ALWAYS_SYNC_ATTRS:
                return f.attr
            if (
                f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in self.NP_ALIASES
                and node.args
                and self._deviceish(node.args[0], host_names)
            ):
                return f"np.{f.attr}"
        elif isinstance(f, ast.Name) and f.id in self.CAST_NAMES:
            if len(node.args) == 1 and self._deviceish(
                node.args[0], host_names
            ):
                return f.id
        return None

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        graph = self._graph
        if graph is None:
            return out
        seen_sites: set[int] = set()  # closures appear under their
        # enclosing function's qname too; flag each site once
        for q, chain in self._hot.items():
            fi = graph.functions[q]
            if fi.sf is not sf:
                continue
            names = " -> ".join(graph.display(x) for x in chain)
            host_names = self._host_names(fi.node)
            offloaded = self._offloaded_ids(fi.node)
            # full walk, nested closures included: the dispatchers run
            # their `_run` closures inline on the compute thread
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call) or id(n) in seen_sites:
                    continue
                if id(n) in offloaded:
                    continue  # runs on another thread, off-queue
                site = self._sync_site(n, host_names)
                if site is None:
                    continue
                seen_sites.add(id(n))
                f = sf.finding(
                    self.code,
                    n,
                    f"implicit device->host sync `{site}` on the decode "
                    f"hot path (reachable via {names}): it blocks the "
                    "serialized compute queue for a device round trip — "
                    "return the lazy array and fetch off-queue "
                    "(executor.fetch), or mark the deliberate sync with "
                    "`# bbtpu: noqa[BB011]` naming the owner",
                    chain=tuple(graph.display(x) for x in chain),
                )
                if f:
                    out.append(f)
        return out


class UnbucketedJitShapeRule(Rule):
    """BB012: a static (shape-bearing) argument of a jit entry call must
    not derive from a data-dependent Python value without a bucketer on
    the path.

    Every distinct static-arg tuple is a full XLA retrace+recompile;
    feeding a request-dependent raw size (`t = hidden.shape[1]`,
    `r = sum(counts)`) straight into `t=`/`r=`/`max_pages=` compiles
    once PER REQUEST SHAPE — the recompile storm the pow2 bucketing
    discipline (next_pow2 / plan_prefill_chunks) exists to cap at
    O(log T). The rule follows simple local assignments (closures read
    their enclosing frame): a bucketer call anywhere on the derivation
    path clears the value; a derivation showing data sources (.shape,
    len()/int()/sum()/max()/min()) with no bucketer is flagged; anything
    else (attributes, constants, config) stays quiet. Scope: entries
    defined in runtime/ and ops/.
    """

    code = "BB012"
    name = "unbucketed-jit-shape-arg"
    summary = "data-dependent static jit arg with no pow2 bucketing"

    BUCKETERS = ("next_pow2", "plan_prefill_chunks")
    _DATA_RE = re.compile(
        r"\bint\(|\blen\(|\bsum\(|\bmax\(|\bmin\(|\.shape\b"
    )
    _BUCKET_RE = re.compile(r"\bnext_pow2\(|\bplan_prefill_chunks\(")

    def __init__(self):
        self._entries: dict[str, _JitEntry] = {}

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._entries = {
            name: e
            for name, e in scan_jit_entries(files).items()
            if "runtime/" in e.path or "ops/" in e.path
        }

    @staticmethod
    def _assign_map(fn: ast.AST) -> dict[str, list[str]]:
        """name -> [assigned expr text, ...] over the whole function,
        nested closures included (they read the enclosing frame)."""
        out: dict[str, list[str]] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                targets = []
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        targets.append(t.id)
                    elif isinstance(t, ast.Tuple):
                        targets.extend(
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        )
                text = _expr_text(n.value)
                for t in targets:
                    out.setdefault(t, []).append(text)
            elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name
            ):
                out.setdefault(n.target.id, []).append(_expr_text(n.value))
        return out

    def _classify(
        self, expr: ast.AST, assigns: dict[str, list[str]]
    ) -> str | None:
        """'bucketed' | 'raw' | None (unknown/benign). Bucketer wins."""
        texts = [_expr_text(expr)]
        names = [
            n.id for n in ast.walk(expr) if isinstance(n, ast.Name)
        ]
        seen = set()
        for _ in range(5):  # bounded transitive expansion
            nxt: list[str] = []
            for name in names:
                if name in seen:
                    continue
                seen.add(name)
                for text in assigns.get(name, ()):
                    texts.append(text)
                    try:
                        nxt.extend(
                            n.id
                            for n in ast.walk(ast.parse(text, mode="eval"))
                            if isinstance(n, ast.Name)
                        )
                    except SyntaxError:
                        pass
            if not nxt:
                break
            names = nxt
        blob = " ".join(texts)
        if self._BUCKET_RE.search(blob):
            return "bucketed"
        if self._DATA_RE.search(blob):
            return "raw"
        return None

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        if not self._entries:
            return out
        for fn in _outermost_functions(sf.tree):
            assigns = self._assign_map(fn)
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                entry = self._entries.get(_call_name(n))
                if entry is None:
                    continue
                checks: list[tuple[str, ast.AST]] = []
                for kw in n.keywords:
                    if kw.arg and kw.arg in entry.statics:
                        checks.append((kw.arg, kw.value))
                for i, a in enumerate(n.args):
                    if i < len(entry.params) and (
                        entry.params[i] in entry.statics
                    ):
                        checks.append((entry.params[i], a))
                for arg_name, val in checks:
                    if self._classify(val, assigns) != "raw":
                        continue
                    f = sf.finding(
                        self.code,
                        n,
                        f"jit entry `{entry.name}(...)`: static shape "
                        f"arg `{arg_name}={_expr_text(val)}` derives "
                        "from a data-dependent value with no bucketer "
                        "(next_pow2/plan_prefill_chunks) on the path — "
                        "every distinct value is a full XLA recompile; "
                        "bucket it like executor._step's bb/tb/pb",
                    )
                    if f:
                        out.append(f)
        return out


class UseAfterDonationRule(Rule):
    """BB013: no read of a donated argument after the jitted call
    returns.

    `donate_argnames` hands the argument's buffer to XLA — after the
    call it is DELETED; any later read raises (or worse, on some
    backends, reads garbage). The `arena_k`/`arena_v` slabs are exactly
    this class: every step donates the KV arena and must thread the
    RETURNED arena forward. The rule tracks the donated argument
    expressions (and the manager-attribute they alias) per function,
    lineno-ordered; a Load of the same expression after the donating
    call is flagged. Reads inside except handlers stay quiet — the
    `_arena_consumed` self-heal contract probes donated buffers
    deliberately — and a reassignment of the root name kills tracking
    (rebinding to the returned buffers is the correct pattern).
    """

    code = "BB013"
    name = "use-after-donation"
    summary = "donated jit argument read after the call"

    def __init__(self):
        self._donating: dict[str, _JitEntry] = {}

    def prepare(self, files: list[SourceFile], graph) -> None:
        self._donating = {
            name: e
            for name, e in scan_jit_entries(files).items()
            if e.donated
        }

    @staticmethod
    def _in_handler(node: ast.AST, handlers: list[set[int]]) -> bool:
        return any(id(node) in h for h in handlers)

    def visit_file(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        if not self._donating:
            return out
        handler_sets = [
            {id(x) for stmt in h.body for x in ast.walk(stmt)}
            for h in ast.walk(sf.tree)
            if isinstance(h, ast.ExceptHandler)
        ]
        for fn in _outermost_functions(sf.tree):
            # donating calls in source order, with their donated exprs
            donations: list[tuple[int, ast.Call, list[str]]] = []
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                entry = self._donating.get(_call_name(n))
                if entry is None:
                    continue
                exprs: list[str] = []
                for kw in n.keywords:
                    if kw.arg and kw.arg in entry.donated:
                        exprs.append(_expr_text(kw.value))
                for i, a in enumerate(n.args):
                    if i < len(entry.params) and (
                        entry.params[i] in entry.donated
                    ):
                        exprs.append(_expr_text(a))
                if exprs:
                    donations.append((n.lineno, n, exprs))
            if not donations:
                continue
            # a Store of the donated expression (or its root name) after
            # the call rebinds it to the RETURNED buffers — the correct
            # pattern (`ak, av = span_step(ak, av, ...)`) — and kills
            # tracking from that line on. Same-line counts: the rebind
            # statement IS the donating call.
            kills: dict[str, list[int]] = {}
            for n in ast.walk(fn):
                targets: list[ast.AST] = []
                if isinstance(n, ast.Assign):
                    targets = list(n.targets)
                elif isinstance(n, (ast.AugAssign, ast.For)):
                    targets = [n.target]
                for t in targets:
                    elts = (
                        t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t]
                    )
                    for e in elts:
                        text = _expr_text(e)
                        if text:
                            kills.setdefault(text, []).append(n.lineno)
            # mutually exclusive if/else arms: a read in the sibling arm
            # of the donating call never executes after it
            branch_pairs: list[tuple[set[int], set[int]]] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.If) and n.orelse:
                    body_ids = {
                        id(x) for s in n.body for x in ast.walk(s)
                    }
                    else_ids = {
                        id(x) for s in n.orelse for x in ast.walk(s)
                    }
                    branch_pairs.append((body_ids, else_ids))
            for call_line, call, exprs in donations:
                call_ids = {id(x) for x in ast.walk(call)}
                flagged: set[str] = set()
                for n in ast.walk(fn):
                    if id(n) in call_ids:
                        continue  # the donating call's own arguments
                    if not isinstance(
                        n, (ast.Subscript, ast.Attribute, ast.Name)
                    ):
                        continue
                    if not isinstance(
                        getattr(n, "ctx", None), ast.Load
                    ):
                        continue
                    line = getattr(n, "lineno", 0)
                    if line <= call_line:
                        continue
                    text = _expr_text(n)
                    if text not in exprs or text in flagged:
                        continue
                    root = text.split("[")[0].split(".")[0]
                    if any(
                        call_line <= k <= line
                        for k in kills.get(text, [])
                        + kills.get(root, [])
                    ):
                        continue  # rebound to the returned buffers
                    if self._in_handler(n, handler_sets):
                        continue  # _arena_consumed recovery contract
                    if any(
                        (id(call) in b and id(n) in e)
                        or (id(call) in e and id(n) in b)
                        for b, e in branch_pairs
                    ):
                        continue  # mutually exclusive branches
                    f = sf.finding(
                        self.code,
                        n,
                        f"`{text}` was DONATED to "
                        f"`{_call_name(call)}(...)` on line {call_line} "
                        "(donate_argnames) — its buffer is deleted when "
                        "the call returns; thread the returned arrays "
                        "forward instead of re-reading the donated ones",
                    )
                    if f:
                        out.append(f)
                    # at most one finding per donated expr per call:
                    # every later read is the same defect
                    flagged.add(text)
        return out


def make_rules() -> list[Rule]:
    """Fresh rule instances (BB006 keeps cross-file state)."""
    return [
        SpeculativeWriteRule(),
        BlockingUnderLockRule(),
        LockOrderRule(),
        WireCompatRule(),
        EnvRegistryRule(),
        CounterSurfacingRule(),
        ExactTensorCompareRule(),
        RawClockRule(),
        AsyncBlockingRule(),
        FireAndForgetTaskRule(),
        HotPathHostSyncRule(),
        UnbucketedJitShapeRule(),
        UseAfterDonationRule(),
    ]


ALL_CODES = tuple(r.code for r in make_rules())
