"""Speculative decoding: tree math, acceptance rules, and e2e equivalence.

Ports the intent of /root/reference/tests/test_spe_dec_tree.py,
test_spec_decoding_verify.py, test_speculative_generation.py. The e2e
invariant: greedy speculative decode produces EXACTLY the tokens of plain
greedy decode.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.spec.tree import DraftTree, chain_tree, tree_attention_mask
from bloombee_tpu.spec.verify import accept_greedy, accept_sampling


def test_tree_invariants():
    #       0   1          (roots)
    #      2 3   4
    #      5
    tree = DraftTree(
        tokens=np.asarray([10, 11, 12, 13, 14, 15]),
        parents=np.asarray([-1, -1, 0, 0, 1, 2]),
    )
    assert tree.depths().tolist() == [0, 0, 1, 1, 1, 2]
    a = tree.ancestors_or_self()
    assert a[5].tolist() == [True, False, True, False, False, True]
    assert tree.path_to(5) == [0, 2, 5]
    assert tree.children_of(-1).tolist() == [0, 1]
    assert tree.children_of(0).tolist() == [2, 3]
    m = tree_attention_mask(tree)
    assert m.shape == (6, 6)
    assert not m[2, 1]  # sibling branch invisible

    with pytest.raises(ValueError):
        DraftTree(tokens=np.asarray([1, 2]), parents=np.asarray([1, -1]))

    chain = chain_tree(np.asarray([5, 6, 7]))
    assert chain.parents.tolist() == [-1, 0, 1]
    assert np.all(chain.ancestors_or_self() == np.tril(np.ones((3, 3), bool)))


def _logits_for(vocab, *winners):
    """[len(winners), vocab] logits whose argmax at row i is winners[i]."""
    out = np.zeros((len(winners), vocab), np.float32)
    for i, w in enumerate(winners):
        out[i, w] = 5.0
    return out


def test_accept_greedy_path():
    # tree: 0(tok 3) -> 1(tok 7) -> 2(tok 9); sibling 3(tok 8) under 0
    tree = DraftTree(
        tokens=np.asarray([3, 7, 9, 8]),
        parents=np.asarray([-1, 0, 1, 0]),
    )
    vocab = 16
    root_logits = _logits_for(vocab, 3)[0]  # target wants 3 -> accept node 0
    logits = _logits_for(vocab, 7, 9, 1, 0)  # node0->7, node1->9, node2->1
    accepted, bonus = accept_greedy(tree, root_logits, logits)
    assert accepted == [0, 1, 2]
    assert bonus == 1  # argmax after node 2

    # target disagrees at the root: nothing accepted, bonus = target's pick
    accepted, bonus = accept_greedy(tree, _logits_for(vocab, 5)[0], logits)
    assert accepted == [] and bonus == 5

    # target accepts node 0 then picks the sibling branch (node 3, tok 8)
    logits2 = _logits_for(vocab, 8, 9, 1, 2)  # node0 -> 8 => descend to 3
    accepted, bonus = accept_greedy(
        tree, _logits_for(vocab, 3)[0], logits2
    )
    assert accepted == [0, 3] and bonus == 2


def test_accept_sampling_peaked_matches_greedy():
    tree = DraftTree(
        tokens=np.asarray([3, 7]), parents=np.asarray([-1, 0])
    )
    vocab = 8
    root_logits = _logits_for(vocab, 3)[0] * 10
    logits = _logits_for(vocab, 7, 2)[:2] * 10
    draft_probs = np.full((2, vocab), 1e-3)
    draft_probs[0, 3] = 1.0
    draft_probs[1, 7] = 1.0
    rng = np.random.default_rng(0)
    accepted, bonus = accept_sampling(
        tree, root_logits, logits, draft_probs, rng, temperature=1.0
    )
    assert accepted == [0, 1] and bonus == 2


def test_e2e_speculative_equals_greedy(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=64, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=64, page_size=4),
        ]
        for s in servers:
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        # the model drafts for itself -> high acceptance, exact equality
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(5)[None, :]
        n_new = 10

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        # may overshoot by the accepted path length; the generated prefix
        # must match plain greedy token-for-token
        assert spec_ids.shape[1] >= input_ids.shape[1] + n_new
        plain_ids = await model.generate(
            input_ids, max_new_tokens=spec_ids.shape[1] - input_ids.shape[1]
        )
        np.testing.assert_array_equal(spec_ids, plain_ids)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_batch4_equals_greedy(tmp_path):
    """Batched speculative decoding (reference speculative_model.py:33-117
    per-sample trees): 4 rows with different prompts, per-row accepts, all
    token-exact vs plain batched greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4),
        ]
        for s in servers:
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        rng = np.random.default_rng(7)
        input_ids = rng.integers(0, 128, size=(4, 5))
        n_new = 8

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        assert spec_ids.shape == (4, 5 + n_new)
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_failover_ragged_replay(tmp_path):
    """Kill the preferred tail server between two batched speculative calls
    on one session: recovery replays RAGGED per-row token ids (rows committed
    different counts) and continuation stays token-exact vs plain greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=10.0)
        s_b = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=10.0)
        s_c = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=1.0)
        for s in (s_a, s_b, s_c):
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        rng = np.random.default_rng(11)
        input_ids = rng.integers(0, 128, size=(3, 5))
        session = model.inference_session(64, 3)
        await session.__aenter__()
        used = {x.span.server_info.port for x in session._spans}
        assert s_b.port in used and s_c.port not in used

        first = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=5, session=session
        )
        # rows committed ragged counts; kill the preferred tail server
        await s_b.stop()
        more = await generate_speculative(
            model, drafter, first[:, -1:], max_new_tokens=5, session=session
        )
        await session.__aexit__(None, None, None)
        final = np.concatenate([first, more[:, 1:]], axis=1)
        plain = await model.generate(input_ids, max_new_tokens=10)
        np.testing.assert_array_equal(final, plain)

        for s in (s_a, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_pruned_midchain(tmp_path):
    """Mid-chain pruning (reference backend.py:395-410 + client restore):
    span 0 keeps only MidLMHead survivors, downstream spans verify the
    smaller tree, the client restores kept logits to original indices —
    tokens stay exactly equal to plain greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                         registry=rc(), compute_dtype=jnp.float32,
                         num_pages=256, page_size=4)
        s2 = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                         registry=rc(), compute_dtype=jnp.float32,
                         num_pages=256, page_size=4)
        await s1.start()
        await s2.start()

        keeps = []
        orig_prune = s1._prune_tree

        def spy(out, prune):
            k = orig_prune(out, prune)
            keeps.append(k)
            return k

        s1._prune_tree = spy

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2)
        )
        rng = np.random.default_rng(5)
        input_ids = rng.integers(0, 128, size=(2, 5))
        n_new = 8

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            prune_threshold=0.45,
        )
        assert spec_ids.shape == (2, 5 + n_new)
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)
        # the pruner actually ran and dropped nodes in at least one round
        assert keeps, "server-side pruner never invoked"
        assert any(
            k is not None and (k < 0).any() for k in keeps
        ), "pruner never dropped a node (threshold too low for this test)"

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_drafter_cached_matches_uncached():
    """The prefix-KV cached drafter must build exactly the trees the
    recompute-everything path built (same top-k expansions)."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.utils.tree import unstack_params

    spec = ModelSpec(
        family="llama", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64,
    )
    blocks = [
        init_block_params(jax.random.PRNGKey(i), spec) for i in range(2)
    ]
    rng = jax.random
    client = {
        "embed": rng.normal(rng.PRNGKey(7), (64, 32)) * 0.1,
        "norm": jnp.ones((32,)),
        "lm_head": rng.normal(rng.PRNGKey(8), (32, 64)) * 0.1,
    }
    model = LocalJaxDraftModel(spec, blocks, client)
    drafter = GreedyTreeDrafter(model, branching=(2, 2, 1))
    contexts = [[1, 5, 9, 2], [3, 3, 3, 3, 3, 7]]

    trees, probs = drafter.build_batch(contexts)

    # uncached reference: full recompute per level via last_logits_ragged
    def build_uncached(ctx):
        tokens, parents = [], []
        frontier = [(-1, list(ctx))]
        for width in drafter.branching:
            seqs = [f[1] for f in frontier]
            logits = model.last_logits_ragged(seqs)
            top = np.argsort(-logits, axis=-1)[:, :width]
            new_frontier = []
            for fi, (parent, path) in enumerate(frontier):
                for tok in top[fi]:
                    idx = len(tokens)
                    tokens.append(int(tok))
                    parents.append(parent)
                    new_frontier.append((idx, path + [int(tok)]))
            frontier = new_frontier
        return tokens, parents

    # numerical agreement first (the robust contract: cached and uncached
    # attention reduce in different orders, so logits match to tolerance)
    l_cached = model.prefill_ragged(contexts)[2]
    l_uncached = model.last_logits_ragged(contexts)
    np.testing.assert_allclose(l_cached, l_uncached, atol=1e-4, rtol=1e-4)
    for r, ctx in enumerate(contexts):
        ref_tokens, ref_parents = build_uncached(ctx)
        np.testing.assert_array_equal(trees[r].tokens, ref_tokens)
        np.testing.assert_array_equal(trees[r].parents, ref_parents)


def test_shape_chooser_prefers_depth_when_accepts_are_high():
    from bloombee_tpu.spec.shape import (
        AcceptanceStats,
        choose_branching,
        expected_accepted,
        tree_nodes,
    )

    assert tree_nodes((2, 2, 1)) == 11

    hot = AcceptanceStats()
    cold = AcceptanceStats()
    for _ in range(200):
        hot.observe(3, (2, 2, 2))   # everything accepts
        cold.observe(0, (2, 2, 2))  # nothing ever accepts
    deep, shallow = (2, 2, 2), (4,)
    assert expected_accepted(deep, hot) > expected_accepted(shallow, hot)
    chosen_hot = choose_branching(hot, budget_nodes=15)
    chosen_cold = choose_branching(cold, budget_nodes=15)
    assert len(chosen_hot) >= 2  # deep pays off when accepts are high
    assert tree_nodes(chosen_cold) <= tree_nodes(chosen_hot)


def test_e2e_adaptive_drafter_stays_exact(tmp_path):
    """Adaptive tree shaping retunes branching mid-generation; tokens must
    stay exactly greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=256,
                        page_size=4)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2),
            adaptive=True, retune_every=2,
        )
        input_ids = np.arange(5)[None, :]
        n_new = 14
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)
        assert drafter.stats.tries.sum() > 0  # feedback actually flowed
        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_sampling(tmp_path):
    """Sampling-mode speculative decode (SpecInfer rejection sampling): at
    near-zero temperature it equals greedy; at temperature 1 it runs and is
    reproducible per seed."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=256,
                        page_size=4)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(2 * 5).reshape(2, 5) % 120
        n_new = 6

        cold = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1e-4, seed=0,
        )
        greedy = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(cold, greedy)

        hot1 = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1.0, seed=7,
        )
        hot2 = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1.0, seed=7,
        )
        assert hot1.shape == (2, 5 + n_new)
        np.testing.assert_array_equal(hot1, hot2)  # seed-reproducible

        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_accept_sampling_preserves_target_distribution():
    """The emitted token (accepted draft or bonus) must be distributed
    exactly as softmax(target/T), with DETERMINISTIC top-k proposals — the
    way our drafter actually proposes (the SpecInfer min(1,p/q) rule would
    be biased here)."""
    from bloombee_tpu.spec.verify import _softmax

    vocab = 6
    rng0 = np.random.default_rng(42)
    target_logits = rng0.normal(size=vocab) * 1.5
    drafter_logits = rng0.normal(size=vocab) * 1.5
    top2 = np.argsort(-drafter_logits)[:2]  # deterministic proposals
    for temperature in (1.0, 0.5):
        counts = np.zeros(vocab)
        n = 40000
        rng = np.random.default_rng(0)
        tree = DraftTree(
            tokens=np.asarray(top2), parents=np.asarray([-1, -1])
        )
        dummy = np.zeros((2, vocab), np.float32)
        for _ in range(n):
            accepted, bonus = accept_sampling(
                tree, target_logits, dummy, _softmax(drafter_logits[None]),
                rng, temperature=temperature,
            )
            tok = int(tree.tokens[accepted[0]]) if accepted else bonus
            counts[tok] += 1
        emp = counts / n
        tgt = _softmax(target_logits[None] / temperature)[0]
        tv = 0.5 * np.abs(emp - tgt).sum()
        assert tv < 0.02, (temperature, tv, emp.round(3), tgt.round(3))


def test_e2e_speculative_qwen2_family(tmp_path):
    """Non-llama family drafting + tree-verifying through the swarm: the
    drafter registry is family-generic (round-4 verdict: it hardwired
    llama's block_forward). Qwen2 brings biased qkv projections."""
    import transformers as tf

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = tf.Qwen2Config(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(4)
    hf = tf.Qwen2ForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "qwen2")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="q", start=0, end=2, model_dir=d, registry=rc(),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await server.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="q", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(5)[None, :]
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=8
        )
        assert spec_ids.shape[1] >= input_ids.shape[1] + 8
        plain_ids = await model.generate(
            input_ids, max_new_tokens=spec_ids.shape[1] - input_ids.shape[1]
        )
        np.testing.assert_array_equal(spec_ids, plain_ids)

        await server.stop()
        await reg.stop()

    asyncio.run(run())


def test_drafter_rejects_unsupported_family():
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.spec.drafter import LocalJaxDraftModel

    spec = ModelSpec(
        family="bloom", hidden_size=32, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=4, head_dim=8,
        num_hidden_layers=2, vocab_size=64, alibi=True, norm_type="ln",
        mlp_type="gelu_tanh",
    )
    with pytest.raises(NotImplementedError, match="ALiBi"):
        LocalJaxDraftModel(spec, [], {})


# ---------------------------------------------- batched tree verification
def _save_tiny_llama(path, seed=0):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(seed)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    hf.save_pretrained(str(path), safe_serialization=True)
    return str(path), hf, config


def _hf_greedy(hf_model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor(np.asarray(input_ids)),
            max_new_tokens=max_new_tokens, do_sample=False, use_cache=True,
        )
    return out.numpy()


def test_e2e_spec_batch_concurrent_sessions_token_identical(
    tmp_path, monkeypatch
):
    """Two concurrently speculating sessions on a --spec-batch server
    coalesce their tree-verify steps into shared ragged dispatches
    (tree_group_dispatches > 0, width ~2) and stay token-identical to a
    solo-sequential speculative run AND to HF greedy. Session A carries 3
    rows drafted by a DIFFERENT tiny model (low, uneven acceptance), so
    rows finish at different rounds and the client's live-row window
    exercises `rows` slices on tree steps."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.wire.rpc import connect

    d, hf, config = _save_tiny_llama(tmp_path / "model", seed=0)
    d2, _, _ = _save_tiny_llama(tmp_path / "drafter", seed=1)
    rng = np.random.default_rng(19)
    prompts = [
        rng.integers(0, config.vocab_size, size=(3, 5)),
        rng.integers(0, config.vocab_size, size=(1, 6)),
    ]
    drafter_dirs = [d2, d]  # weak drafter for A (ragged finishes), self for B
    n_new = 8

    async def run_spec(spec_batch, window):
        monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", window)
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4, max_batch=8,
                        spec_batch=spec_batch)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m"
        )
        info = None
        try:
            coros = [
                generate_speculative(
                    model,
                    GreedyTreeDrafter(
                        LocalJaxDraftModel.from_dir(dd), branching=(2, 1)
                    ),
                    p, max_new_tokens=n_new,
                )
                for p, dd in zip(prompts, drafter_dirs)
            ]
            if spec_batch:
                outs = await asyncio.gather(*coros)
            else:
                outs = [await c for c in coros]
            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            await conn.close()
        finally:
            await s.stop()
            await reg.stop()
        return [np.asarray(o) for o in outs], s, info

    # window > client think-time (drafter forward, ~0.5s/round on CPU):
    # a tighter window lets the sessions phase-lock and never group
    batched, s_b, info = asyncio.run(run_spec(True, "2000"))
    solo, s_u, _ = asyncio.run(run_spec(False, "0"))

    # the batched run really coalesced; the flag-off run never did
    assert s_b.tree_group_dispatches > 0
    assert s_u.tree_group_dispatches == 0
    assert s_b.tree_steps > 0 and s_u.tree_steps > 0

    for got_b, got_u, p in zip(batched, solo, prompts):
        np.testing.assert_array_equal(got_b, got_u)
        ref = _hf_greedy(hf, p, got_b.shape[1] - p.shape[1])
        np.testing.assert_array_equal(got_b, ref)

    # observability: the new spec counters surface in rpc_info
    assert info["spec_batch"] is True
    assert info["tree_group_dispatches"] == s_b.tree_group_dispatches
    assert info["mean_tree_batch_width"] >= 2.0
    assert info["tree_steps"] == s_b.tree_steps
    assert info["spec_tokens_drafted"] > 0
    assert 0.0 < info["spec_accept_rate"] <= 1.0
    sess_spec = info["session_spec"]
    assert len(sess_spec) == 2
    for entry in sess_spec.values():
        assert entry["drafted"] > 0
        assert 0.0 <= entry["accept_rate"] <= 1.0
    # the self-drafted session accepts nearly everything; the weak-drafted
    # one does not — per-session rates really are measured per session
    rates = sorted(e["accept_rate"] for e in sess_spec.values())
    assert rates[1] > rates[0]


@pytest.mark.chaos
def test_e2e_spec_batch_fault_mid_verify_replays_solo(
    tmp_path, monkeypatch
):
    """A group dispatch that fails AFTER the device step wrote every
    member's tree rows must roll all members back to their pre-dispatch
    lengths and replay them solo — tokens stay exactly HF greedy."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    d, hf, config = _save_tiny_llama(tmp_path / "model", seed=0)
    # the window must exceed client think-time (drafter forward ~0.5s on
    # CPU here), else the two sessions phase-lock anti-phase and never group
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "2000")
    # JIT compiles block the event loop for 10-15s at a time here (the
    # solo-replay tree shapes compile fresh after the injected fault), so
    # any keepalive fence the ambient chaos matrix configures fires during
    # a stall and takes down every loopback conn at once — including the
    # registry announce, which fail-louds recovery with MissingBlocksError.
    # An injected half-open partition is conversely undetectable without
    # keepalives and hangs the run. Both knobs are orthogonal to what this
    # test targets (group rollback + solo replay token-exactness) and have
    # dedicated coverage in test_session_lease, so strip them while keeping
    # the rest of the ambient chaos (delays, resets). The fault plan is
    # built lazily once per process, so reset its cache to pick up the env.
    from bloombee_tpu.wire import faults

    monkeypatch.setenv("BBTPU_KEEPALIVE_S", "0")
    monkeypatch.setenv("BBTPU_CHAOS_PARTITION_P", "0")
    monkeypatch.setattr(faults, "_env_checked", False)
    monkeypatch.setattr(faults, "_active_plan", None)
    rng = np.random.default_rng(23)
    prompts = [
        rng.integers(0, config.vocab_size, size=(1, 5)) for _ in range(2)
    ]
    n_new = 8

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4, max_batch=8,
                        spec_batch=True)
        await s.start()

        # fail the FIRST group dispatch after its speculative KV writes
        # landed: recovery must truncate every member before the solo
        # replay. Group dispatches all flow through the universal
        # ragged_group entry point, so that's the interposition surface.
        orig = s.executor.ragged_group
        calls = {"n": 0}

        def flaky(*a, **kw):
            out = orig(*a, **kw)
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected fault after device dispatch")
            return out

        s.executor.ragged_group = flaky

        # ambient chaos (CORRUPT entry) can corrupt a span-output reply of
        # this test too: the digest reject takes the standard short fault
        # ban, and in a ONE-server swarm the default 15s ban outlasts the
        # default 3-attempt recovery budget no matter what. Short bans +
        # a generous retry budget keep that heal structurally survivable
        # (and the token-identity assertion still covers it) without
        # stripping corruption from the ambient plan.
        from bloombee_tpu.client.config import ClientConfig

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m",
            config=ClientConfig(
                max_retries=10, ban_timeout=0.5, ban_max=2.0,
            ),
        )
        try:
            outs = await asyncio.gather(*(
                generate_speculative(
                    model,
                    GreedyTreeDrafter(
                        LocalJaxDraftModel.from_dir(d), branching=(2, 1)
                    ),
                    p, max_new_tokens=n_new,
                )
                for p in prompts
            ))
            assert calls["n"] >= 1, "no group dispatch ever formed"
            assert s.batch_solo_steps >= 2  # both members replayed solo
            for p, got in zip(prompts, outs):
                got = np.asarray(got)
                ref = _hf_greedy(hf, p, got.shape[1] - p.shape[1])
                np.testing.assert_array_equal(got, ref)
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


def test_e2e_spec_batch_after_prefix_adoption(tmp_path, monkeypatch):
    """Prefix adoption composes with batched tree verification: a cold
    session publishes a shared prompt prefix; two later speculating
    sessions (one adopting that prefix) group their tree-verify steps and
    stay HF-exact."""
    from bloombee_tpu.client.config import ClientConfig
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    d, hf, config = _save_tiny_llama(tmp_path / "model", seed=0)
    # window > client think-time (drafter forward ~0.5s on CPU), else the
    # two identically-paced sessions phase-lock and never share a window
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "2000")
    shared = (np.arange(8)[None, :] * 7 + 1) % config.vocab_size
    long_ids = np.concatenate(
        [shared, (np.arange(8)[None, :] * 3 + 2) % config.vocab_size],
        axis=1,
    )
    other = np.random.default_rng(29).integers(
        0, config.vocab_size, size=(1, 6)
    )
    n_new = 6

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4, max_batch=8,
                        spec_batch=True, prefix_cache=True)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m",
            config=ClientConfig(use_push=False, prefix_cache=True),
        )

        def drafter():
            return GreedyTreeDrafter(
                LocalJaxDraftModel.from_dir(d), branching=(2, 1)
            )

        try:
            # cold pass publishes the shared prefix pages
            cold = await generate_speculative(
                model, drafter(), shared, max_new_tokens=n_new
            )
            ref = _hf_greedy(hf, shared, cold.shape[1] - shared.shape[1])
            np.testing.assert_array_equal(cold, ref)

            outs = await asyncio.gather(
                generate_speculative(
                    model, drafter(), long_ids, max_new_tokens=n_new
                ),
                generate_speculative(
                    model, drafter(), other, max_new_tokens=n_new
                ),
            )
            for p, got in zip((long_ids, other), outs):
                got = np.asarray(got)
                ref = _hf_greedy(hf, p, got.shape[1] - p.shape[1])
                np.testing.assert_array_equal(got, ref)
            assert s.manager.prefix_stats()["prefix_hits"] >= 1
            assert s.tree_group_dispatches > 0
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


def test_drafter_autotune_shrinks_on_acceptance_collapse():
    """Closed feedback loop, collapse direction: when observed acceptance
    goes to zero, the adaptive chooser's per-node cost makes every node a
    net loss and the tree shrinks monotonically to the smallest candidate;
    the drafter's measured accept_rate tracks the collapse."""
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter
    from bloombee_tpu.spec.shape import tree_nodes

    drafter = GreedyTreeDrafter(
        model=None, branching=(2, 2, 2), adaptive=True, retune_every=1
    )
    assert drafter.accept_rate == 0.0  # nothing observed yet
    drafter.observe([3, 3])  # one warm round: everything accepted
    assert drafter.accept_rate == 1.0

    for _ in range(3):
        drafter.observe([0, 0])  # collapse reaches every level's stats
    sizes = [tree_nodes(drafter.branching)]
    for _ in range(40):
        drafter.observe([0, 0])  # sustained acceptance collapse
        sizes.append(tree_nodes(drafter.branching))
    assert all(b <= a for a, b in zip(sizes, sizes[1:])), sizes
    assert sizes[-1] < sizes[0]
    assert sizes[-1] == min(
        tree_nodes(c) for c in ((2,), (4,), (2, 1), (2, 2))
    )  # collapsed all the way to the cheapest viable candidate
    assert drafter.accept_rate < 0.1

    # recovery direction: sustained full accepts regrow the tree
    deep = GreedyTreeDrafter(
        model=None, branching=(2, 2, 2), adaptive=True, retune_every=1
    )
    for _ in range(40):
        deep.observe([3, 3])
    assert len(deep.branching) >= 2
    assert deep.accept_rate == 1.0
