"""Normalization ops.

Matches HF Llama semantics bit-for-bit in fp32 (reference kernel:
/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:111 `rms_norm`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def layer_norm(
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    eps: float = 1e-5,
) -> jax.Array:
    """Standard LayerNorm (Bloom/Falcon families), fp32 accumulation."""
    in_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(in_dtype)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with fp32 accumulation, output cast back to input dtype.

    Order of operations matches HF LlamaRMSNorm: normalize in fp32, cast back to
    the input dtype, then multiply by the (un-cast) weight.
    """
    in_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return weight * y.astype(in_dtype)
