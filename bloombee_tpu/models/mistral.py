"""Mistral family: Llama structure + (optional) all-layer sliding windows.

The reference covers Mistral implicitly through HF wrappers; here it is the
llama weight layout (identical parameter names) with every layer sliding
when config.sliding_window is set. The window mask semantics match HF
(each query attends to at most `sliding_window` keys including itself).
"""

from __future__ import annotations

from typing import Any

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.llama.block import (
    HF_BLOCK_KEYS,
    convert_hf_block_params,
)
from bloombee_tpu.models.spec import ModelSpec


def mistral_spec_from_hf(config: Any) -> ModelSpec:
    sliding = getattr(config, "sliding_window", None)
    return ModelSpec(
        family="mistral",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=getattr(config, "head_dim", None)
        or config.hidden_size // config.num_attention_heads,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 10000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        layer_types=("sliding",) if sliding else (),
        sliding_window=sliding or 0,
    )


register_family(
    Family(
        "mistral", mistral_spec_from_hf, HF_BLOCK_KEYS,
        convert_block=convert_hf_block_params,
    )
)
