"""Async RPC over length-prefixed msgpack frames on TCP.

Provides the reference's RPC surface (SURVEY.md section 2.7 / 5): unary calls
(`rpc_info`, `rpc_forward`, `rpc_backward`), one-way pushes (`rpc_push`), and
bidirectional streams (`rpc_inference`) — the semantics of hivemind's
libp2p/protobuf transport re-provided natively. One TCP connection multiplexes
any number of concurrent calls and streams by frame id.

Frame layout: [u32 frame_len][u32 header_len][msgpack header][tensor blobs].
The header carries method, metadata (msgpack dict — the reference's MSGPack
sidecar), and per-tensor codec metas (see tensor_codec).

Codec scheduling (wire/pipeline.py): tensor (de)serialization runs OFF
the event loop in a shared codec pool, bounded and ordered per
connection. Sends hold a FlowLimiter slot around encode+write so a slow
peer backpressures its own connection, not the loop; receives are
decoded concurrently but dispatched by a single drain task in arrival
order, so frames for one stream never reorder, and the bounded drain
queue turns a slow consumer into TCP backpressure. BBTPU_WIRE_PIPELINE=0
restores the seed's synchronous scheduling (byte-identical frames).

Codec negotiation: each side piggybacks its supported codec names
("cd" header key) on the first frames it sends. Older peers ignore
unknown header keys and never advertise, so until (unless) an advert
arrives the send path assumes tensor_codec.LEGACY_WIRE_CODECS — mixed
swarms degrade byte-for-byte to the legacy codec choice, and a future
codec ships without a flag day.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Awaitable, Callable

import msgpack
import numpy as np

from bloombee_tpu.utils import clock, env, lockwatch
from bloombee_tpu.wire import faults, tensor_codec
from bloombee_tpu.wire.pipeline import CodecPipeline, decode_now

logger = logging.getLogger(__name__)

MAX_FRAME = 1 << 31  # 2 GiB

env.declare(
    "BBTPU_KEEPALIVE_S", float, 0.0,
    "keepalive interval: idle connections exchange ping/pong frames so a "
    "half-open TCP peer (partition without FIN/RST) is detected instead of "
    "hanging forever in recv(); a connection silent past ~2.5x the interval "
    "is declared dead. 0 disables keepalives (seed behavior)",
)


class RpcError(RuntimeError):
    pass


class ConnectionClosed(RpcError):
    pass


class OverloadedError(RpcError):
    """Structured retriable shed: the peer is healthy but past its
    admission high-watermark, so it refused NEW work instead of letting it
    rot in the queue until the deadline aborts it. Carries the server's
    suggested retry delay; clients treat this as reroute-then-backoff (a
    short overload penalty, never a fault ban)."""

    def __init__(self, msg: str = "server overloaded",
                 retry_after_ms: int | None = None):
        super().__init__(msg)
        self.retry_after_ms = (
            int(retry_after_ms) if retry_after_ms is not None else None
        )


def error_to_meta(e: Exception) -> dict:
    """Serialize a handler failure into an err-frame meta. Overload sheds
    keep their structure (code + retry hint) across the wire; everything
    else degrades to the legacy message string, which old peers parse
    unchanged."""
    meta = {"error": f"{type(e).__name__}: {e}"}
    if isinstance(e, OverloadedError):
        meta["code"] = "overloaded"
        if e.retry_after_ms is not None:
            meta["retry_after_ms"] = int(e.retry_after_ms)
    return meta


def error_from_meta(meta: dict) -> RpcError:
    """Inverse of error_to_meta; unknown codes fall back to plain RpcError
    so a newer peer's error classes never break an older client."""
    msg = meta.get("error", "remote error")
    if meta.get("code") == "overloaded":
        return OverloadedError(msg, retry_after_ms=meta.get("retry_after_ms"))
    return RpcError(msg)


# frame types whose payload is decoded by the ordered receive path; unary
# reqs and pushes decode inside their own handler task instead (unordered
# by design, and a bad unary payload answers with an err frame rather
# than killing the connection)
_ORDERED_FRAMES = frozenset({"sopen", "sitem", "res"})


def _frame_buffers(header: dict, blobs: list) -> list:
    """Vectored frame encoding: [u32 frame_len][u32 header_len][header]
    followed by the tensor payloads AS-IS (bytes or memoryview), ready for
    writer.writelines — the payloads are never copied into an
    intermediate frame buffer."""
    header = dict(header)
    header["bl"] = [len(b) for b in blobs]
    h = msgpack.packb(header, use_bin_type=True)
    total = 4 + len(h) + sum(len(b) for b in blobs)
    bufs = [struct.pack("<II", total, len(h)) + h]
    bufs.extend(blobs)
    return bufs


def _encode_frame(header: dict, blobs: list) -> bytes:
    """Contiguous frame bytes (tests and tooling; the hot path writes the
    _frame_buffers sequence without this join)."""
    return b"".join(bytes(b) for b in _frame_buffers(header, blobs))


class Stream:
    """One side of a bidirectional stream (the rpc_inference session carrier,
    reference: handler.py:798-1257)."""

    def __init__(self, conn: "Connection", stream_id: int, meta: dict,
                 tensors: list[np.ndarray]):
        self.conn = conn
        self.id = stream_id
        self.open_meta = meta
        self.open_tensors = tensors
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._closed_local = False
        self._closed_remote = False

    async def send(self, meta: dict, tensors: list[np.ndarray] | None = None,
                   compression: bool = True) -> None:
        if self._closed_local:
            raise RpcError("stream closed")
        await self.conn._send_payload(
            {"t": "sitem", "id": self.id, "meta": meta}, tensors, compression
        )

    async def recv(self) -> tuple[dict, list[np.ndarray]] | None:
        """Next item, or None once the peer half-closed."""
        if self._closed_remote and self._inbox.empty():
            return None
        item = await self._inbox.get()
        if item is None:
            self._closed_remote = True
            return None
        if isinstance(item, Exception):
            raise item
        return item

    async def close(self, meta: dict | None = None) -> None:
        """Half-close: tells the peer no more items will be sent."""
        if not self._closed_local:
            self._closed_local = True
            if not self.conn.is_closing():
                await self.conn._send(
                    {"t": "send", "id": self.id, "meta": meta or {}}, []
                )

    def _push_inbound(self, item) -> None:
        self._inbox.put_nowait(item)


UnaryHandler = Callable[[dict, list[np.ndarray]], Awaitable[tuple[dict, list[np.ndarray]]]]
StreamHandler = Callable[[Stream], Awaitable[None]]
PushHandler = Callable[[dict, list[np.ndarray]], Awaitable[None]]


class Connection:
    """A multiplexed RPC connection (either direction)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        unary_handlers: dict[str, UnaryHandler] | None = None,
        stream_handlers: dict[str, StreamHandler] | None = None,
        push_handlers: dict[str, PushHandler] | None = None,
        peer: tuple[str, int] | None = None,
        keepalive_s: float | None = None,
        legacy_wire: bool = False,
        codecs: frozenset | None = None,
    ):
        self.reader = reader
        self.writer = writer
        self.unary_handlers = unary_handlers or {}
        self.stream_handlers = stream_handlers or {}
        self.push_handlers = push_handlers or {}
        # remote (host, port) when known — fault rules target peers by port
        self.peer = peer or self._peername(writer)
        self.fault_plan = faults.get_plan()
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, Stream] = {}
        self._unary_tasks: dict[int, asyncio.Task] = {}
        self._tasks: set[asyncio.Task] = set()
        self._send_lock = lockwatch.async_lock("rpc.send")
        self._reader_task: asyncio.Task | None = None
        self._closed = asyncio.Event()
        # --- codec negotiation + off-loop pipeline -----------------------
        # legacy_wire emulates a pre-negotiation peer (compat shim for
        # mixed-swarm tests and the bench's legacy leg): never advertise,
        # ignore adverts, codec work stays synchronous on the loop
        self.legacy_wire = bool(legacy_wire)
        self.codecs_local = (
            frozenset(codecs) | {"raw"} if codecs is not None
            else tensor_codec.supported_codecs()
        )
        # until the peer advertises, assume the pre-negotiation contract
        self.peer_codecs = tensor_codec.LEGACY_WIRE_CODECS
        self._advertised = self.legacy_wire
        self.pipeline = CodecPipeline(
            name="%s:%s" % self.peer if self.peer else ""
        )
        if self.legacy_wire:
            self.pipeline.enabled = False
        self._rx_queue: asyncio.Queue | None = (
            asyncio.Queue(maxsize=self.pipeline.depth)
            if self.pipeline.enabled else None
        )
        self._drain_task: asyncio.Task | None = None
        self.on_close: Callable[["Connection"], None] | None = None
        # keepalive state: last_recv only advances on frames that survive
        # fault injection, so an injected partition looks exactly as silent
        # as a real half-open peer
        self.keepalive_s = (
            env.get("BBTPU_KEEPALIVE_S") if keepalive_s is None
            else keepalive_s
        )
        self.last_recv = clock.monotonic()
        self.keepalives_sent = 0
        self._keepalive_task: asyncio.Task | None = None

    @staticmethod
    def _peername(writer: asyncio.StreamWriter) -> tuple[str, int] | None:
        try:
            name = writer.get_extra_info("peername")
            return (name[0], name[1]) if name else None
        except Exception:
            return None

    # ------------------------------------------------------------------ setup
    def start(self) -> None:
        self._reader_task = asyncio.create_task(self._read_loop())
        if self._rx_queue is not None:
            self._drain_task = asyncio.create_task(self._rx_drain_loop())
        if self.keepalive_s and self.keepalive_s > 0:
            self._keepalive_task = asyncio.create_task(self._keepalive_loop())

    def is_closing(self) -> bool:
        return self._closed.is_set() or self.writer.is_closing()

    async def close(self) -> None:
        self._closed.set()
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._drain_task is not None:
            self._drain_task.cancel()
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        for t in list(self._tasks):
            t.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        self._fail_all(ConnectionClosed("connection closed"))

    def _fail_all(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()
        for s in self._streams.values():
            s._push_inbound(exc)

    def abort(self, reason: str = "connection aborted") -> None:
        """Fail every pending call/stream locally and kill the transport
        with no FIN handshake. Used to fence a peer we have decided is gone
        (keepalive timeout, superseded by a session resume, expired lease):
        everyone blocked on this connection unwedges NOW instead of
        whenever TCP notices."""
        self._fail_all(ConnectionClosed(reason))
        self._closed.set()
        if self._drain_task is not None:
            self._drain_task.cancel()
        try:
            transport = self.writer.transport
            if transport is not None:
                transport.abort()
        except Exception:
            pass
        self._streams.clear()

    # -------------------------------------------------------------- client API
    async def call(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        timeout: float | None = None,
        compression: bool = True,
    ) -> tuple[dict, list[np.ndarray]]:
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        await self._send_payload(
            {"t": "req", "id": rid, "m": method, "meta": meta or {}},
            tensors, compression,
        )
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            # the caller is abandoning this call: tell the server so it can
            # stop computing for a client that will never read the reply
            if not self.is_closing():
                try:
                    await self._send({"t": "cancel", "id": rid}, [])
                except Exception:
                    pass  # best-effort; the timeout still propagates
            raise
        finally:
            self._pending.pop(rid, None)

    async def push(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        compression: bool = True,
    ) -> None:
        """Fire-and-forget (the reference's rpc_push plane)."""
        await self._send_payload(
            {"t": "push", "id": 0, "m": method, "meta": meta or {}},
            tensors, compression,
        )

    async def open_stream(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        compression: bool = True,
    ) -> Stream:
        rid = next(self._ids)
        stream = Stream(self, rid, meta or {}, tensors or [])
        self._streams[rid] = stream
        await self._send_payload(
            {"t": "sopen", "id": rid, "m": method, "meta": meta or {}},
            tensors, compression,
        )
        return stream

    # --------------------------------------------------------------- internals
    def _allowed_codecs(self) -> frozenset:
        """Send-codec set for this peer: the negotiated intersection (the
        from_wire compat-filtering spirit, applied to codecs)."""
        return (self.peer_codecs & self.codecs_local) | {"raw"}

    async def _send_payload(
        self,
        header: dict,
        tensors: list[np.ndarray] | None,
        compression: bool = True,
    ) -> None:
        """Encode + send one tensor-carrying frame. Serialization runs in
        the codec pool under a FlowLimiter slot: a peer that drains slowly
        inflates this connection's send times, the AIMD law shrinks its
        concurrency, and waiters park on the limiter instead of stacking
        encoded frames in memory or convoying the event loop."""
        async with self.pipeline.tx_slot():
            tm, blobs = await self.pipeline.encode(
                tensors or [], compression, self._allowed_codecs()
            )
            header["tm"] = tm
            await self._send(header, blobs)

    async def _send(self, header: dict, blobs: list) -> None:
        if not self._advertised:
            # negotiation advert rides the first outgoing frame(s): older
            # peers ignore unknown header keys, newer peers switch their
            # send codecs to the intersection. Repeated until one frame is
            # known written, so an injected drop can't eat the advert.
            header = dict(header)
            header["cd"] = sorted(self.codecs_local)
        if self.fault_plan is not None:
            # may sleep (delayed frame), raise after killing the transport
            # (injected reset / mid-stream close / stalled write), mutate
            # header+blobs in place (injected payload corruption — the
            # frame below is encoded from the mutated pair), or ask for a
            # silent discard (injected partition blackhole)
            if await self.fault_plan.on_send(self, header, blobs) == "drop":
                return
        bufs = _frame_buffers(header, blobs)
        async with self._send_lock:
            self.writer.writelines(bufs)
            await self.writer.drain()
        self._advertised = True

    async def _keepalive_loop(self) -> None:
        """Ping on idle, declare the peer dead when silent too long.

        A half-open connection (peer partitioned without FIN/RST) never
        errors recv() — this loop is the only thing that unwedges it: after
        ~2.5 intervals with no inbound frame the transport is aborted and
        every pending call/stream fails with ConnectionClosed, exactly like
        a real disconnect (retry paths must not special-case it)."""
        interval = self.keepalive_s
        try:
            while not self._closed.is_set():
                await clock.async_sleep(interval / 2)
                idle = clock.monotonic() - self.last_recv
                if idle >= 2.5 * interval:
                    logger.warning(
                        "keepalive timeout after %.2fs silence from %s",
                        idle, self.peer,
                    )
                    self.abort("keepalive timeout")
                    break
                if idle >= interval / 2:
                    try:
                        await self._send({"t": "ping", "id": 0}, [])
                        self.keepalives_sent += 1
                    except Exception:
                        pass  # the read loop will surface the real error
        except asyncio.CancelledError:
            pass

    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self.reader.readexactly(8)
                total, hlen = struct.unpack("<II", head)
                if total > MAX_FRAME:
                    raise RpcError(f"frame too large: {total}")
                body = await self.reader.readexactly(total - 4)
                header = msgpack.unpackb(body[:hlen], raw=False)
                # zero-copy receive: slice the frame body into memoryviews
                # so raw-codec payloads reach np.frombuffer uncopied
                mv = memoryview(body)
                blobs = []
                off = hlen
                for blen in header.get("bl", []):
                    blobs.append(mv[off : off + blen])
                    off += blen
                if self.fault_plan is not None:
                    act = await self.fault_plan.on_read(self, header)
                    if act == "drop":
                        continue  # injected stall/loss: frame never arrives
                self.last_recv = clock.monotonic()
                cd = header.get("cd")
                if cd and not self.legacy_wire:
                    self.peer_codecs = frozenset(str(c) for c in cd)
                await self._ingest(header, blobs)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except asyncio.CancelledError:
            return
        except Exception as e:  # pragma: no cover
            logger.exception("rpc read loop error: %s", e)
        finally:
            self._closed.set()
            if self._keepalive_task is not None:
                self._keepalive_task.cancel()
            await self._flush_drain()
            self._fail_all(ConnectionClosed("peer disconnected"))
            # close our side of the transport too: asyncio.Server.wait_closed
            # blocks until every accepted connection's transport is closed
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close is not None:
                self.on_close(self)

    async def _ingest(self, header: dict, blobs: list) -> None:
        """Route one inbound frame toward _dispatch.

        Pipelined: ordered frames get their decode submitted to the codec
        pool NOW (overlapping the next socket read) and everything goes
        through the bounded FIFO the drain task empties — a full queue
        stalls this coroutine, which stalls the socket: TCP backpressure.
        Legacy sync mode decodes in-line and dispatches immediately (the
        seed's exact scheduling)."""
        t = header["t"]
        if self._rx_queue is None:
            if t in _ORDERED_FRAMES:
                self._dispatch(
                    header, decode_now(header.get("tm") or [], blobs)
                )
            else:
                self._dispatch(header, blobs)
            return
        aw = None
        if t in _ORDERED_FRAMES:
            aw = self.pipeline.decode_submit(header.get("tm") or [], blobs)
        if self._rx_queue.full():
            self.pipeline.rx_backpressure_waits += 1
        self.pipeline.note_rx_depth(self._rx_queue.qsize() + 1)
        await self._rx_queue.put((header, blobs, aw))

    async def _rx_drain_loop(self) -> None:
        """Single consumer of the receive queue: awaits each frame's decode
        in ARRIVAL order before dispatching, so off-loop concurrency can
        never reorder the frames of one stream."""
        try:
            while True:
                item = await self._rx_queue.get()
                if item is None:
                    return
                header, blobs, aw = item
                if aw is not None:
                    try:
                        payload = await aw
                    except Exception as e:
                        self._decode_failed(header, e)
                        continue
                else:
                    payload = blobs
                try:
                    self._dispatch(header, payload)
                except Exception:
                    logger.exception("rpc dispatch error")
                    self.abort("dispatch error")
                    return
        except asyncio.CancelledError:
            pass

    async def _flush_drain(self) -> None:
        """Read-loop teardown: frames already queued (a res some caller is
        awaiting) still dispatch before everyone gets failed."""
        if self._drain_task is None or self._drain_task.done():
            return
        try:
            self._rx_queue.put_nowait(None)
        except asyncio.QueueFull:
            self._drain_task.cancel()
        try:
            await self._drain_task
        except (asyncio.CancelledError, Exception):
            pass

    def _decode_failed(self, header: dict, exc: Exception) -> None:
        """A frame that parsed but whose payload fails the codec is a peer
        bug (or injected corruption): fail the one call/stream it belongs
        to and keep the connection — the other multiplexed users are
        unaffected."""
        t, rid = header.get("t"), header.get("id")
        err = RpcError(f"codec error on {t} frame: {exc}")
        logger.warning("%s from %s", err, self.peer)
        if t == "res":
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(err)
        elif t == "sitem":
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound(err)
        elif t == "sopen":
            # no Stream exists yet on this side; tell the opener
            self._spawn(self._send(
                {"t": "err", "id": rid, "meta": {"error": str(err)}}, []
            ))

    def _dispatch(self, header: dict, payload: list) -> None:
        """payload: decoded tensors for ordered frames (sopen/sitem/res),
        raw blob buffers for req/push — their handler tasks decode
        off-loop themselves so a bad unary payload answers with an err
        frame instead of killing the connection."""
        t = header["t"]
        rid = header["id"]
        if t == "req":
            task = asyncio.create_task(self._handle_unary(header, payload))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            # indexed by request id so a later "cancel" frame can stop it
            self._unary_tasks[rid] = task
            task.add_done_callback(
                lambda _t, rid=rid: self._unary_tasks.pop(rid, None)
            )
        elif t == "cancel":
            # peer abandoned a unary call (client-side wait_for timeout):
            # stop the in-flight handler; no reply is expected
            task = self._unary_tasks.pop(rid, None)
            if task is not None and not task.done():
                task.cancel()
        elif t == "push":
            self._spawn(self._handle_push(header, payload))
        elif t == "sopen":
            stream = Stream(self, rid, header.get("meta", {}), payload)
            self._streams[rid] = stream
            self._spawn(self._handle_stream(header["m"], stream))
        elif t == "sitem":
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound((header.get("meta", {}), payload))
        elif t == "send":
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound(None)
        elif t == "res":
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_result((header.get("meta", {}), payload))
        elif t == "err":
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(error_from_meta(header.get("meta", {})))
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound(error_from_meta(header.get("meta", {})))
        elif t == "ping":
            # keepalive probe: answer even when we have no keepalive loop of
            # our own, so a one-sided rollout still detects half-open links
            self._spawn(self._send_pong())
        elif t == "pong":
            pass  # liveness already recorded by the read loop
        else:
            logger.warning("unknown frame type %r", t)

    def _spawn(self, coro) -> None:
        task = asyncio.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _send_pong(self) -> None:
        try:
            if not self.is_closing():
                await self._send({"t": "pong", "id": 0}, [])
        except Exception:
            pass  # a dying transport surfaces through the read loop

    async def _handle_unary(self, header: dict, blobs: list) -> None:
        rid = header["id"]
        method = header["m"]
        try:
            handler = self.unary_handlers.get(method)
            if handler is None:
                raise RpcError(f"no such method: {method}")
            tensors = await self.pipeline.decode_wait(
                header.get("tm", []), blobs
            )
            meta, out = await handler(header.get("meta", {}), tensors)
            await self._send_payload({"t": "res", "id": rid, "meta": meta}, out)
        except asyncio.CancelledError:
            # cancelled by a peer "cancel" frame (abandoned call) or by
            # connection teardown: either way nobody is reading the reply
            logger.debug("unary handler %s cancelled", method)
        except Exception as e:
            logger.debug("unary handler %s failed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": rid, "meta": error_to_meta(e)},
                    [],
                )

    async def _handle_push(self, header: dict, blobs: list) -> None:
        method = header["m"]
        handler = self.push_handlers.get(method)
        if handler is None:
            logger.warning("no push handler for %s", method)
            return
        tensors = await self.pipeline.decode_wait(header.get("tm", []), blobs)
        try:
            await handler(header.get("meta", {}), tensors)
        except Exception as e:
            logger.exception("push handler %s failed: %s", method, e)

    async def _handle_stream(self, method: str, stream: Stream) -> None:
        handler = self.stream_handlers.get(method)
        if handler is None:
            await self._send(
                {"t": "err", "id": stream.id,
                 "meta": {"error": f"no such stream method: {method}"}},
                [],
            )
            return
        try:
            await handler(stream)
        except OverloadedError as e:
            # expected shed under load, not a server fault: no stack trace
            logger.info("stream handler %s shed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": stream.id, "meta": error_to_meta(e)},
                    [],
                )
        except Exception as e:
            logger.exception("stream handler %s failed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": stream.id, "meta": error_to_meta(e)},
                    [],
                )
        finally:
            self._streams.pop(stream.id, None)


class RpcServer:
    """Listening side: accepts connections, one Connection per peer."""

    def __init__(
        self,
        unary_handlers: dict[str, UnaryHandler] | None = None,
        stream_handlers: dict[str, StreamHandler] | None = None,
        push_handlers: dict[str, PushHandler] | None = None,
        host: str = "0.0.0.0",
        port: int = 0,
        keepalive_s: float | None = None,
        legacy_wire: bool = False,
        codecs: frozenset | None = None,
    ):
        self.unary_handlers = unary_handlers or {}
        self.stream_handlers = stream_handlers or {}
        self.push_handlers = push_handlers or {}
        self.host = host
        self.port = port
        self.keepalive_s = keepalive_s
        self.legacy_wire = legacy_wire
        self.codecs = codecs
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[Connection] = set()
        # cumulative pings from already-closed connections; live ones are
        # summed on demand (keepalives_sent property)
        self._keepalives_closed = 0
        # same pattern for the codec-pipeline counters
        self._pipeline_closed = {
            "tx_jobs": 0, "rx_jobs": 0,
            "rx_depth_max": 0, "rx_backpressure_waits": 0,
        }

    @property
    def keepalives_sent(self) -> int:
        return self._keepalives_closed + sum(
            c.keepalives_sent for c in self._conns
        )

    def pipeline_stats(self) -> dict:
        """Aggregated off-loop codec pipeline counters: live connections
        plus the already-closed accumulator. Surfaced through rpc_info so
        cli/health --probe can print them (BB006)."""
        out = dict(self._pipeline_closed)
        out["conns"] = len(self._conns)
        out["enabled"] = False
        out["tx_limit"] = 0
        for c in self._conns:
            s = c.pipeline.stats()
            out["enabled"] = out["enabled"] or s["enabled"]
            out["tx_jobs"] += s["tx_jobs"]
            out["rx_jobs"] += s["rx_jobs"]
            out["rx_backpressure_waits"] += s["rx_backpressure_waits"]
            out["rx_depth_max"] = max(out["rx_depth_max"], s["rx_depth_max"])
            out["tx_limit"] = max(out["tx_limit"], s["tx_limit"])
        return out

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def _on_connect(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = Connection(
            reader, writer,
            self.unary_handlers, self.stream_handlers, self.push_handlers,
            keepalive_s=self.keepalive_s,
            legacy_wire=self.legacy_wire, codecs=self.codecs,
        )
        conn.on_close = self._on_conn_close
        self._conns.add(conn)
        conn.start()

    def _on_conn_close(self, conn: Connection) -> None:
        if conn in self._conns:
            self._keepalives_closed += conn.keepalives_sent
            s = conn.pipeline.stats()
            acc = self._pipeline_closed
            acc["tx_jobs"] += s["tx_jobs"]
            acc["rx_jobs"] += s["rx_jobs"]
            acc["rx_backpressure_waits"] += s["rx_backpressure_waits"]
            acc["rx_depth_max"] = max(acc["rx_depth_max"], s["rx_depth_max"])
        self._conns.discard(conn)

    async def stop(self) -> None:
        for c in list(self._conns):
            await c.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def abort(self) -> None:
        """Hard-kill (crash fault injection): abort every live
        connection's transport — no close frame, no FIN handshake, every
        pending call on the peer side fails exactly like a process death
        — and close the listener without waiting for it."""
        for c in list(self._conns):
            c.abort("server crashed")
        if self._server is not None:
            self._server.close()
            self._server = None


async def connect(
    host: str,
    port: int,
    unary_handlers: dict[str, UnaryHandler] | None = None,
    stream_handlers: dict[str, StreamHandler] | None = None,
    push_handlers: dict[str, PushHandler] | None = None,
    keepalive_s: float | None = None,
    legacy_wire: bool = False,
    codecs: frozenset | None = None,
) -> Connection:
    reader, writer = await asyncio.open_connection(host, port)
    conn = Connection(
        reader, writer, unary_handlers, stream_handlers, push_handlers,
        peer=(host, port), keepalive_s=keepalive_s,
        legacy_wire=legacy_wire, codecs=codecs,
    )
    conn.start()
    return conn
