"""Attention ops (GQA, arbitrary boolean masks).

Replaces the reference decode/prefill attention kernels
(/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:665 `mha_llama`,
:733 `mha_gen_llama`). One masked implementation covers prefill (causal mask),
decode (length mask over the paged cache) and speculative tree verify (arbitrary
tree mask, reference backend.py:596-652) — the mask is data, not code.

Softmax accumulates in fp32; matmuls stay in the input dtype so the MXU runs
bfloat16 on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, hd] -> [B, S, Hkv*n_rep, hd] (GQA share pattern)."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d))
    return x.reshape(b, s, h * n_rep, d)


def masked_attention(
    q: jax.Array,  # [B, T, H, hd]
    k: jax.Array,  # [B, S, Hkv, hd]
    v: jax.Array,  # [B, S, Hkv, hd]
    mask: jax.Array,  # [B, T, S] bool (True = attend) or [B, 1, T, S]
    scale: float | None = None,
) -> jax.Array:
    """Full masked attention; returns [B, T, H, hd] in q.dtype."""
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # [B, H, T, S]
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if mask.ndim == 3:
        mask = mask[:, None, :, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhts,bshd->bthd", probs, v)
    return out


def causal_mask(t: int, offset: int = 0, s: int | None = None) -> jax.Array:
    """[T, S] causal mask: query i (absolute position offset+i) sees keys <= it."""
    if s is None:
        s = offset + t
    q_pos = offset + jnp.arange(t)[:, None]
    k_pos = jnp.arange(s)[None, :]
    return k_pos <= q_pos
