"""Span step parity: paged prefill + decode vs dense HF reference.

The TPU-native analogue of /root/reference/tests/test_block_exact_match.py's
step-wise inference check (atol 1e-3), across a whole span with the paged KV
arena instead of dense concat caches.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import HF_BLOCK_KEYS, convert_hf_block_params
from bloombee_tpu.models.llama.config import llama_spec_from_hf
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.utils.tree import stack_params


@pytest.fixture(scope="module")
def setup():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=256,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    spec = llama_spec_from_hf(config)
    layers = []
    for layer in model.model.layers:
        sd = layer.state_dict()
        layers.append(
            convert_hf_block_params({k: sd[k].numpy() for k in HF_BLOCK_KEYS})
        )
    params = stack_params(layers)
    return model, config, spec, params


def hf_span_forward(model, hidden_t: torch.Tensor) -> np.ndarray:
    """Dense full-sequence forward through all decoder layers (no norm/head)."""
    t = hidden_t.shape[1]
    position_ids = torch.arange(t).unsqueeze(0).expand(hidden_t.shape[0], -1)
    cos, sin = model.model.rotary_emb(hidden_t, position_ids)
    h = hidden_t
    with torch.no_grad():
        for layer in model.model.layers:
            out = layer(h, position_embeddings=(cos, sin), attention_mask=None)
            h = out[0] if isinstance(out, tuple) else out
    return h.numpy()


def make_executor(spec, params, **kw):
    manager = CacheManager(
        num_layers=spec.num_hidden_layers,
        num_pages=32,
        page_size=4,
        n_kv_heads=spec.num_key_value_heads,
        head_dim=spec.head_dim,
        dtype=jnp.float32,
    )
    ex = SpanExecutor(
        params, spec, manager, compute_dtype=jnp.float32, **kw
    )
    return manager, ex


def test_prefill_then_decode_matches_dense(setup):
    model, config, spec, params = setup
    b, total, prefill = 2, 12, 7
    torch.manual_seed(3)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 32) as handle:
            out_pre = ex.prefill(handle, hidden[:, :prefill].numpy())
            np.testing.assert_allclose(
                out_pre, ref[:, :prefill], atol=1e-3, rtol=1e-3
            )
            for i in range(prefill, total):
                out_i = ex.decode(handle, hidden[:, i : i + 1].numpy())
                np.testing.assert_allclose(
                    out_i, ref[:, i : i + 1], atol=1e-3, rtol=1e-3,
                    err_msg=f"decode step {i}",
                )
            assert manager.context_lens(handle).tolist() == [total, total]

    asyncio.run(run())


def test_chunked_prefill_matches(setup):
    model, config, spec, params = setup
    b, total = 1, 11
    torch.manual_seed(4)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params, max_chunk_tokens=4)

    async def run():
        async with manager.allocate(b, 16) as handle:
            out = ex.prefill(handle, hidden.numpy())
            np.testing.assert_allclose(out, ref, atol=1e-3, rtol=1e-3)

    asyncio.run(run())


def test_non_pow2_batch_padding(setup):
    model, config, spec, params = setup
    b, total = 3, 6
    torch.manual_seed(5)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 8) as handle:
            out = ex.prefill(handle, hidden.numpy())
            np.testing.assert_allclose(out, ref, atol=1e-3, rtol=1e-3)

    asyncio.run(run())


def test_speculative_decode_rollback(setup):
    """Write speculative tokens uncommitted, roll back, decode the true token —
    result must match the no-speculation path (paged commit/rollback with the
    arena: reference paged_kv spec-dec routing tests)."""
    model, config, spec, params = setup
    b, prefill = 1, 5
    torch.manual_seed(6)
    hidden = torch.randn(b, prefill + 1, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 16) as handle:
            ex.prefill(handle, hidden[:, :prefill].numpy())
            # speculative garbage tokens, uncommitted
            garbage = np.random.default_rng(0).normal(
                size=(b, 3, config.hidden_size)
            ).astype(np.float32)
            ex.decode(handle, garbage, commit=False)
            assert manager.context_lens(handle).tolist() == [prefill + 3]
            manager.rollback(handle)
            assert manager.context_lens(handle).tolist() == [prefill]
            out = ex.decode(handle, hidden[:, prefill:].numpy())
            np.testing.assert_allclose(
                out, ref[:, prefill:], atol=1e-3, rtol=1e-3
            )

    asyncio.run(run())
