"""Server-side compute runtime: the jitted span step and its executor.

Replaces the reference's TransformerBackend + hivemind Runtime + task-pool
machinery (/root/reference/src/bloombee/server/backend.py:62-1399,
task_pool.py:30-236). The reference routes every request through MPFuture
queues into a separate runtime process; the JAX runtime is process-hostile,
so here a span of blocks is ONE jitted function (`span_step`) — a lax.scan
over stacked per-layer params with the KV arena as a donated carry — and the
executor handles bucketed compilation + host-side plumbing.
"""

from bloombee_tpu.runtime.step import span_step
from bloombee_tpu.runtime.executor import SpanExecutor

__all__ = ["span_step", "SpanExecutor"]
