"""Shared utilities."""
