"""Page-aligned content hash chains for cross-session prefix sharing.

The chain is the identity of a cached KV page: page i's hash covers its own
token ids AND the parent page's hash, so equal hashes imply equal *full
prefixes*, not just equal page contents (SGLang's RadixAttention collapses
the same property into a trie; a chained flat list is equivalent for the
page-granular pool in kv/paged.py and is trivially wire-serializable).

Shared by the client (hash computation over the prompt), the server
(pool lookup + adoption), the bench, and the tests — one definition so a
version skew shows up as a clean cache miss, never a wrong hit.
"""

from __future__ import annotations

import hashlib

import numpy as np

# bumped whenever the hash layout changes: a stale client's chains must
# miss, not alias, a newer server's pool
_CHAIN_VERSION = b"bbtpu-prefix-v1"


def page_hash_chain(ids, page_size: int) -> list[str]:
    """Chained hashes of the *full* pages of one row of token ids.

    Returns one hex digest per complete page (a trailing partial page gets
    no hash — it cannot be shared, its content is still growing). Token ids
    are canonicalized to int64 so the same prompt hashes identically
    whatever integer dtype the caller tokenized into.
    """
    if page_size <= 0:
        raise ValueError(f"page_size must be positive, got {page_size}")
    row = np.asarray(ids).reshape(-1).astype(np.int64)
    chain: list[str] = []
    parent = _CHAIN_VERSION
    for p in range(len(row) // page_size):
        page = row[p * page_size : (p + 1) * page_size]
        digest = hashlib.blake2b(
            parent + page.tobytes(), digest_size=16
        ).hexdigest()
        chain.append(digest)
        parent = digest.encode("ascii")
    return chain
