"""Chaos gate: scripts/chaos.sh must pass as part of the tier-1 suite.

The script replays every chaos-marked test under a fixed BBTPU_CHAOS_*
seed matrix (ambient wire jitter on top of the tests' own seeded fault
plans), so fault-recovery paths are exercised with injected noise on
every run — not only when an operator remembers to soak them. It exits 0
when pytest is unavailable, mirroring the scripts/lint.sh contract.
"""

import pathlib
import re
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_matrix_entries_are_keyval_tokens():
    """The matrix format is KEY=VAL tokens with per-entry defaults — not
    the old positional colon strings, which silently misassigned every
    column to the right of an insertion. Also pins that the Byzantine
    corruption entry exists and forces the integrity layer on (corruption
    is invisible to the transport; without BBTPU_INTEGRITY=1 the entry
    would test nothing)."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    assert len(entries) >= 5, f"matrix lost entries: {entries}"
    known = {
        "SEED", "DELAY_P", "ADMIT", "PARTITION_P", "MIXED", "SPEC",
        "REBALANCE", "CORRUPT", "LOCKWATCH", "JITWATCH", "ARTIFACT",
        "UNIRAGGED", "CODEC", "SIM", "TESTS",
    }
    for entry in entries:
        for tok in entry.split():
            key, sep, val = tok.partition("=")
            assert sep == "=" and key in known and val, (
                f"matrix entry {entry!r} has non-KEY=VAL token {tok!r}"
            )
    assert any("CORRUPT=" in e for e in entries), (
        "no Byzantine corruption entry in the chaos matrix"
    )
    # the swarm-simulator entry replays the metastable-convergence gate
    # (python -m bloombee_tpu.sim --require --smoke) on every chaos run
    assert any("SIM=" in e for e in entries), (
        "no swarm-simulator entry in the chaos matrix"
    )
    # at least one BROAD entry must replay the whole chaos-marked suite:
    # targeted feature entries (TESTS=...) keep the gate inside its wall
    # budget, but whole-suite ambient coverage must never disappear
    assert any("TESTS=" not in e for e in entries), (
        "every matrix entry is targeted; no broad whole-suite entry left"
    )
    # targeted entries must name real files (a typo would silently select
    # nothing and the ledger gate would flag it only at run time)
    for entry in entries:
        for tok in entry.split():
            if tok.startswith("TESTS="):
                for f in tok[len("TESTS="):].split(","):
                    assert (REPO / f).is_file(), (
                        f"matrix entry {entry!r} targets missing file {f}"
                    )
    assert "BBTPU_INTEGRITY=${integrity}" in src
    assert "BBTPU_CHAOS_CORRUPT_P=${CORRUPT}" in src


def test_gate_requires_nonvacuous_ledger():
    """Every matrix entry must run under a recovery-coverage ledger and
    fail when the merged ledger shows zero faults or zero recoveries: a
    probabilistic plan that happened to inject nothing (or whose
    injections never reached recovery machinery) is a vacuous green, and
    the gate's whole point is that green means 'recovery ran'."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    assert "BBTPU_CHAOS_LEDGER=" in src, "entries run without a ledger"
    assert "bbtpu-chaos-ledger" in src and "mktemp" in src, (
        "ledger file is not per-entry (entries would bleed coverage "
        "into each other)"
    )
    assert re.search(
        r"python -m bloombee_tpu\.utils\.ledger .*--require", src
    ), "gate never checks the ledger with --require"


def test_gate_requires_nonvacuous_lockwatch():
    """The lock-witness entry follows the same no-vacuous-green contract
    as the ledger: at least one matrix entry runs with BBTPU_LOCKWATCH=1
    and its report is gated with --require, which fails on zero observed
    cross-lock edges or any hierarchy violation/cycle."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    assert any("LOCKWATCH=1" in e for e in entries), (
        "no lock-witness entry in the chaos matrix"
    )
    assert "BBTPU_LOCKWATCH_REPORT=" in src, (
        "witness runs without a report file; nothing to gate on"
    )
    assert re.search(
        r"python -m bloombee_tpu\.utils\.lockwatch .*\\\n\s*--require", src
    ) or re.search(
        r"python -m bloombee_tpu\.utils\.lockwatch .*--require", src
    ), "gate never checks the lock-witness report with --require"


def test_gate_requires_nonvacuous_jitwatch():
    """The compile-witness entry follows the same contract: at least one
    matrix entry runs with BBTPU_JITWATCH=1 and its report is gated with
    --require, which fails on zero observed compiles (vacuous green), a
    missing warmup fence, or any steady-state recompile."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    assert any("JITWATCH=1" in e for e in entries), (
        "no compile-witness entry in the chaos matrix"
    )
    assert "BBTPU_JITWATCH_REPORT=" in src, (
        "witness runs without a report file; nothing to gate on"
    )
    assert re.search(
        r"python -m bloombee_tpu\.utils\.jitwatch .*\\\n\s*--require", src
    ) or re.search(
        r"python -m bloombee_tpu\.utils\.jitwatch .*--require", src
    ), "gate never checks the compile-witness report with --require"


def test_gate_pins_artifact_entry():
    """The compile-artifact entry must exist and be held to BOTH
    strengthened gates: the merged ledger must show the
    server.artifact_fallback_compile recovery point (the corrupt/declined
    fallback path actually ran, not just clean pre-install), and the
    compile witness must pass --preinstalled mode (the pre-installed
    standby warmed up from persistent-cache hits alone — any real warmup
    compile for a pre-installed bucket is a red)."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    artifact = [e for e in entries if "ARTIFACT=1" in e]
    assert artifact, "no compile-artifact entry in the chaos matrix"
    # the jitwatch --preinstalled gate needs the witness on in the same
    # entry, or there is no report to strengthen
    assert all("JITWATCH=1" in e for e in artifact), (
        "ARTIFACT entry runs without the compile witness"
    )
    assert "--require-recovery" in src and (
        "server.artifact_fallback_compile" in src
    ), "ARTIFACT entry is not pinned to the fallback-compile recovery"
    assert "--preinstalled" in src, (
        "ARTIFACT entry never strengthens the jitwatch gate to "
        "--preinstalled mode"
    )
    assert 'artifact_jitwatch_args="--preinstalled"' in src, (
        "--preinstalled is not derived from the ARTIFACT key"
    )


def test_gate_pins_universal_ragged_entry():
    """The universal-ragged entry must exist and force the whole fused
    path: UNIRAGGED=1 derives BOTH fusion flags inside the script (decode
    + tree + chunk rows share one gather only when mixed AND spec
    batching are on), replays the files whose traffic exercises every row
    kind, and carries the compile witness in the same entry so the
    'unified buckets pre-compiled, zero steady recompiles' claim is gated
    — not just asserted in a unit test."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    uni = [e for e in entries if "UNIRAGGED=1" in e]
    assert uni, "no universal-ragged entry in the chaos matrix"
    assert all("JITWATCH=1" in e for e in uni), (
        "UNIRAGGED entry runs without the compile witness"
    )
    assert any("tests/test_universal_ragged.py" in e for e in uni), (
        "UNIRAGGED entry does not replay the universal-ragged tests"
    )
    # the derivation lives in the script, not the matrix line: setting
    # only one fusion flag would silently degrade the entry to PR-8/PR-10
    # behavior and the 'one dispatch' claim would go untested
    assert re.search(
        r'if \[ "\$\{UNIRAGGED\}" != "0" \]; then\s*\n\s*MIXED=1\s*\n'
        r"\s*SPEC=1", src,
    ), "UNIRAGGED does not derive MIXED=1 SPEC=1"


def test_gate_pins_codec_entry():
    """The streaming wire-path entry must exist and force every frame
    through the off-loop codec pool: CODEC=1 derives an inline threshold
    of 0 inside the script (otherwise tiny chaos-sized frames take the
    inline fast path and the ordered-drain/backpressure machinery under
    test never runs), replays the wire-pipeline tests, and pairs with
    CORRUPT so in-flight corruption of pooled decodes is caught by the
    integrity layer and ledgered as a recovery."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    codec = [e for e in entries if "CODEC=1" in e]
    assert codec, "no streaming wire-path entry in the chaos matrix"
    assert any("tests/test_wire_pipeline.py" in e for e in codec), (
        "CODEC entry does not replay the wire-pipeline tests"
    )
    assert all("CORRUPT=" in e for e in codec), (
        "CODEC entry runs without Byzantine corruption; pooled-decode "
        "integrity goes untested"
    )
    # the derivation lives in the script: without inline=0 the pipeline
    # silently short-circuits for small frames and the entry is vacuous
    assert re.search(
        r'if \[ "\$\{CODEC\}" != "0" \]; then\s*\n\s*wire_inline=0', src,
    ), "CODEC does not derive BBTPU_WIRE_PIPELINE_INLINE=0"
    assert "BBTPU_WIRE_PIPELINE_INLINE=${wire_inline}" in src, (
        "derived inline threshold never reaches the test environment"
    )
    assert "BBTPU_WIRE_PIPELINE=1" in src, (
        "chaos entries run without the wire pipeline pinned on"
    )


def test_red_entry_prints_full_reproduction_line():
    """A red entry must print a single copy-pasteable reproduction line:
    the complete derived environment (not just the matrix tokens — those
    hide keepalive/integrity/promotion knobs derived from them) plus the
    exact pytest invocation, and the per-entry wall time."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    assert "reproduce with:" in src
    # the repro line reuses the same env_line the run used — it cannot
    # drift from reality
    assert src.count("env_line=") == 1
    assert re.search(r"echo\s+\"\s+\$\{env_line\} python -m pytest", src), (
        "repro line does not print the derived environment"
    )
    assert "${elapsed}s" in src, "per-entry wall time missing from gate log"


def test_chaos_suite_under_seed_matrix():
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "chaos.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=580,
    )
    assert proc.returncode == 0, (
        f"chaos regressions:\n{proc.stdout[-8000:]}\n{proc.stderr[-4000:]}"
    )
