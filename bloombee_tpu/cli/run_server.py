"""Run a worker server hosting a span of blocks.

Reference: /root/reference/src/bloombee/cli/run_server.py:18-231. Block
selection is automatic when --blocks is omitted: the server measures its
compute throughput, fetches the swarm's current coverage from the registry,
and picks the least-served window (reference block_selection.py).

    python -m bloombee_tpu.cli.run_server /path/to/model \\
        --registry 10.0.0.1:7700 --blocks 0:16 --port 7800
"""

from __future__ import annotations

import argparse
import asyncio
import logging


def parse_adapters(items):
    """NAME=DIR pairs (or bare DIRs, named by basename) -> {name: dir}."""
    if not items:
        return None
    import os

    out = {}
    for item in items:
        name, sep, path = item.partition("=")
        if not sep or os.sep in name or (os.altsep and os.altsep in name):
            # bare DIR (possibly containing '='): name = basename
            name, path = os.path.basename(os.path.normpath(item)), item
        if not name or not path:
            raise SystemExit(f"bad --adapters entry {item!r}: need NAME=DIR")
        if name in out:
            raise SystemExit(f"duplicate adapter name {name!r} in --adapters")
        out[name] = path
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir", help="local HF model directory")
    parser.add_argument("--model-uid", default=None,
                        help="swarm uid (default: model dir name)")
    parser.add_argument("--registry", default="127.0.0.1:7700",
                        help="registry address, or a comma-separated "
                             "replica list host:port,host:port (announces "
                             "go to every replica)")
    parser.add_argument("--blocks", default=None,
                        help="'start:end' or omit for automatic selection")
    parser.add_argument("--num-blocks", type=int, default=None,
                        help="how many blocks to serve when auto-selecting")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--public-host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-pages", type=int, default=256)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--max-chunk-tokens", type=int, default=512)
    parser.add_argument("--max-batch", type=int, default=8,
                        help="continuous batching: coalesce up to this many "
                             "concurrent sessions' single-token decode "
                             "steps into one span dispatch (1 disables; "
                             "gather window via BBTPU_BATCH_WINDOW_MS)")
    parser.add_argument("--mixed-batch", action="store_true", default=None,
                        help="mixed-batch dispatch: fuse a prefill chunk "
                             "and compatible queued decode steps into ONE "
                             "ragged span dispatch (Sarathi-Serve fused "
                             "iterations) instead of a dispatch each; "
                             "needs --prefill-chunk to produce chunks. "
                             "Default follows BBTPU_MIXED_BATCH")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="stall-free scheduling: split prefills into "
                             "chunks of at most this many tokens, each its "
                             "own compute-queue task, so concurrent "
                             "sessions' decode steps interleave between "
                             "chunks (0 = monolithic prefill; default "
                             "follows BBTPU_PREFILL_CHUNK; aging via "
                             "BBTPU_CHUNK_AGE_S)")
    parser.add_argument("--dtype", default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--adapter-dirs", nargs="*", default=None,
                        help="LoRA adapter directories to merge into blocks")
    parser.add_argument("--adapters", nargs="*", default=None,
                        metavar="NAME=DIR",
                        help="per-request switchable LoRA adapters "
                             "(clients pick one via active_adapter; "
                             "bare DIR uses its basename as the name)")
    parser.add_argument("--announce-period", type=float, default=5.0)
    parser.add_argument("--rebalance-period", type=float, default=None,
                        help="seconds between swarm-balance checks; the "
                             "server drains and moves its span when the "
                             "least-served window beats the hysteresis "
                             "(0 disables; default 300, or 0 when --blocks "
                             "pins the span; reference server.py:479-542)")
    parser.add_argument("--drain-timeout", type=float, default=30.0,
                        help="how long a drain (SIGTERM/SIGINT shutdown or "
                             "a rebalance) waits for live sessions before "
                             "exiting / swapping the span under them")
    parser.add_argument("--weight-quant", default=None,
                        choices=["none", "int8", "int4"],
                        help="weight-only quantization for the served span "
                             "(int8 halves / int4 quarters weight HBM "
                             "bytes per decode step; compute stays bf16)")
    parser.add_argument("--attn-sparsity", type=float, default=1.0,
                        help="<1.0: approximate decode attention keeping "
                             "only the top fraction of past keys per query "
                             "(FlexGen Policy.attn_sparsity)")
    parser.add_argument("--offload-layers", type=int, default=0,
                        help="stream the span's last N layers' weights from "
                             "host memory per step (serve spans larger than "
                             "HBM; pair with --weight-quant to shrink the "
                             "streamed bytes)")
    parser.add_argument("--kv-quant", default=None,
                        choices=["none", "int4"],
                        help="KV cache quantization (int4 = ~3.2x capacity)")
    parser.add_argument("--prefix-cache", action="store_true", default=None,
                        help="share KV pages of common prompt prefixes "
                             "across sessions (refcounted hash pool with "
                             "copy-on-write; clients probe before prefill "
                             "and ship only the uncached suffix). Default "
                             "follows BBTPU_PREFIX_CACHE")
    parser.add_argument("--oversubscribe", type=float, default=1.0,
                        help="admit up to this x KV capacity; idle "
                        "sessions' KV parks to host under pressure")
    parser.add_argument("--idle-park-s", type=float, default=5.0,
                        help="a session idle this long may be parked")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel degree over local chips "
                        "(reference --tensor_parallel_devices)")
    parser.add_argument("--sp", type=int, default=1,
                        help="sequence-parallel degree: prefills of >= "
                        "BBTPU_SP_MIN_TOKENS spread over this many local "
                        "chips via ring attention; decode stays "
                        "single-chip paged")
    parser.add_argument("--admit", action="store_true", default=None,
                        help="admission control: past the queue-delay high "
                             "watermark, shed NEW sessions/prefills with a "
                             "retriable `overloaded` error (established "
                             "sessions' decode steps are always admitted; "
                             "heavy clients shed first via per-client "
                             "fair-share accounting). Default follows "
                             "BBTPU_ADMIT")
    parser.add_argument("--admit-high-ms", type=float, default=None,
                        help="queue-delay high watermark in ms before the "
                             "admission controller starts shedding (default "
                             "follows BBTPU_ADMIT_HIGH_MS)")
    parser.add_argument("--session-lease-s", type=float, default=None,
                        help="session lease: a session whose client goes "
                             "silent (no step, no keepalive) this long is "
                             "reaped — its KV pages become evictable cached "
                             "prefix-pool entries, then free. Disconnected "
                             "clients may reconnect-resume a parked session "
                             "within the lease, token-identical and with "
                             "zero prompt replay (0 disables; default "
                             "follows BBTPU_SESSION_LEASE_S)")
    parser.add_argument("--keepalive-s", type=float, default=None,
                        help="wire keepalive interval: ping idle "
                             "connections, declare them dead after ~2.5x "
                             "silence, so half-open TCP (partition, silent "
                             "peer death) is detected instead of hanging "
                             "(0 disables; default follows "
                             "BBTPU_KEEPALIVE_S)")
    parser.add_argument("--standby", action="store_true",
                        help="start as a WARM STANDBY for the span: load "
                             "weights and accept kv_put replication but "
                             "announce JOINING (no routed traffic), then "
                             "self-promote to a serving replica on "
                             "sustained span overload or server loss and "
                             "drain back when the span cools (watermarks "
                             "via --promote-high-ms/--promote-low-ms; "
                             "requires --blocks or --num-blocks matching "
                             "the primary's span)")
    parser.add_argument("--promote-high-ms", type=float, default=None,
                        help="standby promotion high watermark: promote "
                             "when the span's best serving server sustains "
                             "this much predicted queue delay in ms "
                             "(default follows BBTPU_PROMOTE_HIGH_MS)")
    parser.add_argument("--promote-low-ms", type=float, default=None,
                        help="demotion low watermark: a promoted standby "
                             "drains back once other coverage sustains "
                             "below this (default follows "
                             "BBTPU_PROMOTE_LOW_MS)")
    parser.add_argument("--promote-sustain-s", type=float, default=None,
                        help="how long the hot/cool condition must hold "
                             "before promoting/demoting (default follows "
                             "BBTPU_PROMOTE_SUSTAIN_S)")
    parser.add_argument("--promote-jitter-s", type=float, default=None,
                        help="promotion-storm guard: random pre-promotion "
                             "delay bound + re-check so N standbys don't "
                             "all promote at once (default follows "
                             "BBTPU_PROMOTE_JITTER_S)")
    parser.add_argument("--load-advert-s", type=float, default=None,
                        help="republish the live load snapshot at this "
                             "cadence (seconds) when faster than "
                             "--announce-period; 0 keeps the announce "
                             "cadence (default follows BBTPU_LOAD_ADVERT_S)")
    parser.add_argument("--warmup-batches", default="1",
                        help="comma-separated batch buckets to pre-compile "
                        "at startup ('' = skip)")
    parser.add_argument("--artifact-dir", default=None,
                        help="directory for the swarm-shared compile-"
                             "artifact store: persistent JAX compilation "
                             "cache served to peers over artifact_get and "
                             "pre-fetched from covering peers before "
                             "warmup compiles anything (default follows "
                             "BBTPU_ARTIFACT_DIR; unset = no store)")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level)

    import jax.numpy as jnp

    from bloombee_tpu.models.checkpoint import load_spec
    from bloombee_tpu.server.block_selection import (
        choose_best_blocks,
        choose_num_blocks,
    )
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import make_registry
    from bloombee_tpu.swarm.spans import compute_spans

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    # parse the registry spec BEFORE model resolution: a typo'd --registry
    # must fail fast, not after a multi-GB hub download
    registry = make_registry(args.registry)
    from bloombee_tpu.models.hub import resolve_model_dir

    args.model_dir = resolve_model_dir(args.model_dir)
    spec = load_spec(args.model_dir)
    model_uid = args.model_uid or args.model_dir.rstrip("/").split("/")[-1]

    async def run():
        if args.blocks:
            start, end = (int(x) for x in args.blocks.split(":"))
            if args.rebalance_period is None:
                # operator pinned the span: do not auto-move it out from
                # under them unless they ALSO asked for rebalancing
                args.rebalance_period = 0.0
        else:
            infos = await registry.get_module_infos(
                model_uid, range(spec.num_hidden_layers)
            )
            n = args.num_blocks or choose_num_blocks(
                spec, dtype, args.num_pages, args.page_size
            )
            start, end = choose_best_blocks(
                # departing (DRAINING) servers are not coverage
                infos, compute_spans(infos, include_draining=False), n
            )
            logging.info(
                "auto-selected blocks [%d:%d) (%d blocks)", start, end, n
            )

        server = BlockServer(
            model_uid=model_uid, start=start, end=end,
            model_dir=args.model_dir, registry=registry,
            host=args.host, port=args.port, public_host=args.public_host,
            num_pages=args.num_pages, page_size=args.page_size,
            compute_dtype=dtype, max_chunk_tokens=args.max_chunk_tokens,
            max_batch=args.max_batch,
            mixed_batch=args.mixed_batch,
            prefill_chunk=args.prefill_chunk,
            announce_period=args.announce_period,
            adapter_dirs=args.adapter_dirs,
            adapters=parse_adapters(args.adapters),
            tp=args.tp,
            sp=args.sp,
            kv_quant=args.kv_quant,
            weight_quant=args.weight_quant,
            oversubscribe=args.oversubscribe,
            idle_park_s=args.idle_park_s,
            prefix_cache=args.prefix_cache,
            offload_layers=args.offload_layers,
            attn_sparsity=args.attn_sparsity,
            rebalance_period=(
                300.0 if args.rebalance_period is None
                else args.rebalance_period
            ),
            drain_timeout=args.drain_timeout,
            admit=args.admit,
            admit_high_ms=args.admit_high_ms,
            load_advert_s=args.load_advert_s,
            session_lease_s=args.session_lease_s,
            keepalive_s=args.keepalive_s,
            standby=args.standby,
            promote_high_ms=args.promote_high_ms,
            promote_low_ms=args.promote_low_ms,
            promote_sustain_s=args.promote_sustain_s,
            promote_jitter_s=args.promote_jitter_s,
            artifact_dir=args.artifact_dir,
        )
        await server.start()
        if args.warmup_batches:
            batches = tuple(
                int(x) for x in args.warmup_batches.split(",") if x
            )
            server._warmup_task = asyncio.create_task(
                server.warmup(batches)
            )
        from bloombee_tpu.server.throughput import measure_and_announce

        # keep a strong reference: the loop holds tasks only weakly
        server._throughput_task = asyncio.create_task(
            measure_and_announce(server)
        )
        logging.info(
            "server %s serving %s[%d:%d) on port %d",
            server.server_id, model_uid, start, end, server.port,
        )
        # graceful shutdown: SIGTERM/SIGINT announce DRAINING (routing
        # stops sending new sessions), pending session-KV replication is
        # flushed to standbys (so surviving sessions fail over with at
        # most the unsealed tail to replay), in-flight sessions finish up
        # to --drain-timeout, then the span is revoked and the process
        # exits
        import signal

        stop_requested = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(
                    sig,
                    lambda s=sig: (
                        logging.info(
                            "received %s: draining before exit",
                            signal.Signals(s).name,
                        ),
                        stop_requested.set(),
                    ),
                )
            except NotImplementedError:
                pass  # platform without signal handler support
        await stop_requested.wait()
        await server.drain()

    asyncio.run(run())


if __name__ == "__main__":
    main()
