"""Weight offload: serve a span whose tail layers' weights live in HOST
memory and stream to the device per step (reference FlexGen Policy weight
percentages / convert_block.py PipelineParallelWrapper pre-forward H2D).

The offloaded executor must be numerically identical to the fully-resident
one — same arena, same paging, same windows, same adapters — and the e2e
server path must still match HF logits.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import init_block_params
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.utils.tree import stack_params, unstack_params


def _spec(**kw):
    base = dict(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=4, vocab_size=64,
    )
    base.update(kw)
    return ModelSpec(**base)


def _params(spec, n):
    return stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(n)]
    )


def _manager(n):
    return CacheManager(
        num_layers=n, num_pages=32, page_size=4, n_kv_heads=2, head_dim=16,
        dtype=jnp.float32,
    )


def _host_tail(stacked, n_layers, resident):
    layers = unstack_params(stacked, n_layers)
    prefix = stack_params(layers[:resident]) if resident else None
    host = [jax.device_get(p) for p in layers[resident:]]
    return prefix, host


async def _drive(ex, manager, prefill, steps, layers=None, adapter=None):
    outs = []
    async with manager.allocate(prefill.shape[0], 64) as handle:
        outs.append(
            np.asarray(ex.prefill(handle, prefill, layers=layers,
                                  adapter=adapter))
        )
        for s in steps:
            outs.append(
                np.asarray(ex.decode(handle, s, layers=layers,
                                     adapter=adapter))
            )
    return outs


@pytest.mark.parametrize("resident", [0, 2])
def test_offload_matches_resident(resident):
    spec = _spec()
    stacked = _params(spec, 4)
    rng = np.random.default_rng(0)
    prefill = (rng.standard_normal((2, 9, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((2, 1, 64)) * 0.1).astype(np.float32)
             for _ in range(3)]

    m1 = _manager(4)
    full = SpanExecutor(stacked, spec, m1, compute_dtype=jnp.float32)
    want = asyncio.run(_drive(full, m1, prefill, steps))

    prefix, host = _host_tail(stacked, 4, resident)
    m2 = _manager(4)
    off = SpanExecutor(prefix, spec, m2, compute_dtype=jnp.float32,
                       host_layers=host)
    got = asyncio.run(_drive(off, m2, prefill, steps))

    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_offload_sliding_windows_and_subspan():
    """Per-layer sliding windows ride the per-layer steps (gemma-style
    alternating layers), and session sub-span gating skips offloaded
    layers host-side."""
    spec = _spec(
        sliding_window=4,
        layer_types=("sliding", "full", "sliding", "full"),
    )
    stacked = _params(spec, 4)
    rng = np.random.default_rng(1)
    prefill = (rng.standard_normal((1, 7, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((1, 1, 64)) * 0.1).astype(np.float32)
             for _ in range(2)]

    for layers in (None, (1, 3)):
        m1 = _manager(4)
        full = SpanExecutor(stacked, spec, m1, compute_dtype=jnp.float32)
        want = asyncio.run(_drive(full, m1, prefill, steps, layers=layers))
        prefix, host = _host_tail(stacked, 4, 1)
        m2 = _manager(4)
        off = SpanExecutor(prefix, spec, m2, compute_dtype=jnp.float32,
                           host_layers=host)
        got = asyncio.run(_drive(off, m2, prefill, steps, layers=layers))
        for a, b in zip(got, want):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_offload_quantized_weights():
    """int8 weight quantization composes with offload: quantized resident
    == quantized offloaded (identical codes stream from host)."""
    from bloombee_tpu.models import wquant

    spec = _spec()
    stacked = wquant.quantize_span_params(_params(spec, 4), 8)
    rng = np.random.default_rng(2)
    prefill = (rng.standard_normal((2, 5, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((2, 1, 64)) * 0.1).astype(np.float32)]

    m1 = _manager(4)
    full = SpanExecutor(stacked, spec, m1, compute_dtype=jnp.float32)
    want = asyncio.run(_drive(full, m1, prefill, steps))
    prefix, host = _host_tail(stacked, 4, 2)
    m2 = _manager(4)
    off = SpanExecutor(prefix, spec, m2, compute_dtype=jnp.float32,
                       host_layers=host)
    got = asyncio.run(_drive(off, m2, prefill, steps))
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_offload_with_adapter():
    """Per-request LoRA applies identically on offloaded layers (factors
    slice per layer and ride the stream)."""
    spec = _spec()
    stacked = _params(spec, 4)
    rng = np.random.default_rng(3)
    lora = {
        "q_proj": {
            "a": jnp.asarray(rng.standard_normal((4, 64, 4)) * 0.05,
                             jnp.float32),
            "b": jnp.asarray(rng.standard_normal((4, 4, 64)) * 0.05,
                             jnp.float32),
        }
    }
    prefill = (rng.standard_normal((1, 6, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((1, 1, 64)) * 0.1).astype(np.float32)]

    m1 = _manager(4)
    full = SpanExecutor(stacked, spec, m1, compute_dtype=jnp.float32,
                        adapters={"t": lora})
    want = asyncio.run(_drive(full, m1, prefill, steps, adapter="t"))
    prefix, host = _host_tail(stacked, 4, 2)
    m2 = _manager(4)
    off = SpanExecutor(prefix, spec, m2, compute_dtype=jnp.float32,
                       adapters={"t": lora}, host_layers=host)
    got = asyncio.run(_drive(off, m2, prefill, steps, adapter="t"))
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_offload_e2e_matches_hf(tmp_path):
    """A BlockServer with offload_layers serves HF-exact logits through the
    full swarm path (registry + wire + client)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "m")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="m", start=0, end=3, model_dir=d,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=32, page_size=4,
            offload_layers=2,
        )
        assert server.executor.resident == 1
        assert len(server.executor.host_layers) == 2
        await server.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        input_ids = np.arange(8)[None, :]
        out = await model.generate(input_ids, max_new_tokens=4)
        await server.stop()
        await reg.stop()
        return out

    out = asyncio.run(run())
    with torch.no_grad():
        ref = hf.generate(
            torch.tensor(np.arange(8)[None, :]), max_new_tokens=4,
            do_sample=False,
        ).numpy()
    np.testing.assert_array_equal(out, ref)


def test_offload_with_prebuilt_params(tmp_path):
    """BlockServer accepts pre-built params + offload_layers (previously
    an exclusion): the stacked span splits in-process, tail layers move to
    host, and served tokens match a fully-resident server."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(6)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)

    async def run_swarm(offload):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        params, spec = load_span_params(
            str(tmp_path), 0, 3, dtype=jnp.float32
        )
        server = BlockServer(
            model_uid="t", start=0, end=3, params=params, spec=spec,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
            offload_layers=offload,
        )
        await server.start()
        if offload:
            assert len(server.executor.host_layers) == offload
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), RegistryClient("127.0.0.1", reg.port),
            model_uid="t",
        )
        ids_in = np.arange(5)[None, :]
        ids = await dm.generate(ids_in, max_new_tokens=6,
                                server_decode=False)
        await server.stop()
        await reg.stop()
        return ids

    async def run():
        full = await run_swarm(0)
        off = await run_swarm(2)
        np.testing.assert_array_equal(full, off)

    asyncio.run(run())


def test_offload_prebuilt_quantized_host_layers(tmp_path):
    """Pre-built params + offload + --weight-quant must quantize the HOST
    layers too (dense streamed tails would defeat the combination's
    point); tokens match a fully-resident int8 server (per-layer scales
    are identical either way)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.models.wquant import QuantWeight
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(8)
    LlamaForCausalLM(config).eval().to(torch.float32).save_pretrained(
        tmp_path, safe_serialization=True
    )

    async def run_swarm(offload):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        params, spec = load_span_params(
            str(tmp_path), 0, 3, dtype=jnp.float32
        )
        server = BlockServer(
            model_uid="t", start=0, end=3, params=params, spec=spec,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
            offload_layers=offload, weight_quant="int8",
        )
        await server.start()
        if offload:
            assert any(
                isinstance(leaf, QuantWeight)
                for leaf in server.executor.host_layers[0].values()
            ), "host layers were not quantized"
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), RegistryClient("127.0.0.1", reg.port),
            model_uid="t",
        )
        ids_in = np.arange(5)[None, :]
        ids = await dm.generate(ids_in, max_new_tokens=6,
                                server_decode=False)
        await server.stop()
        await reg.stop()
        return ids

    async def run():
        full = await run_swarm(0)
        off = await run_swarm(2)
        np.testing.assert_array_equal(full, off)

    asyncio.run(run())


@pytest.mark.parametrize("resident", [0, 2])
def test_offload_tp2_matches_tp1(resident):
    """Weight offload under TP serving (previously excluded): streamed
    host layers place SHARDED onto the tp mesh per step; outputs must
    match the unsharded offloaded executor."""
    from bloombee_tpu.parallel.serving import make_serving_mesh

    spec = _spec()
    stacked = _params(spec, 4)
    rng = np.random.default_rng(4)
    prefill = (rng.standard_normal((2, 9, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((2, 1, 64)) * 0.1).astype(np.float32)
             for _ in range(3)]

    prefix, host = _host_tail(stacked, 4, resident)

    def run(mesh):
        m = _manager(4)
        ex = SpanExecutor(prefix, spec, m, compute_dtype=jnp.float32,
                          host_layers=host, mesh=mesh)
        return asyncio.run(_drive(ex, m, prefill, steps))

    ref = run(None)
    tp2 = run(make_serving_mesh(2))
    for a, b in zip(tp2, ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_offload_tp2_block_server_e2e(tmp_path):
    """Full swarm path: a tp=2 server streaming 2 offloaded layers serves
    greedy tokens equal to the tp=1 offloaded server."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(9)
    LlamaForCausalLM(config).eval().to(torch.float32).save_pretrained(
        tmp_path, safe_serialization=True
    )

    async def run_swarm(tp):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="t", start=0, end=3, model_dir=str(tmp_path),
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
            offload_layers=2, tp=tp,
        )
        await server.start()
        assert len(server.executor.host_layers) == 2
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), RegistryClient("127.0.0.1", reg.port),
            model_uid="t",
        )
        ids_in = np.arange(5)[None, :]
        ids = await dm.generate(ids_in, max_new_tokens=6,
                                server_decode=False)
        await server.stop()
        await reg.stop()
        return ids

    async def run():
        tp1 = await run_swarm(1)
        tp2 = await run_swarm(2)
        np.testing.assert_array_equal(tp1, tp2)

    asyncio.run(run())
