"""Bloom family: ALiBi attention, LayerNorm, fused-QKV, 4h GELU MLP.

Reference: /root/reference/src/bloombee/models/bloom/ (WrappedBloomBlock
wraps the HF module and converts KV layouts). Here the fused QKV weight is
split to q/k/v at load (HF layout: per head [q, k, v] interleaved) and the
block runs through the generic layer body with alibi=True (no rotary).
"""

from __future__ import annotations

from typing import Any


from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec


def bloom_spec_from_hf(config: Any) -> ModelSpec:
    n_head = getattr(config, "n_head", None) or config.num_attention_heads
    hidden = config.hidden_size
    return ModelSpec(
        family="bloom",
        hidden_size=hidden,
        intermediate_size=4 * hidden,
        num_attention_heads=n_head,
        num_key_value_heads=n_head,
        head_dim=hidden // n_head,
        num_hidden_layers=getattr(config, "n_layer", None)
        or config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=getattr(config, "layer_norm_epsilon", 1e-5),
        tie_word_embeddings=True,
        alibi=True,
        norm_type="ln",
        mlp_type="gelu_tanh",
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    p = f"h.{layer_idx}"
    if not reader.has(f"{p}.input_layernorm.weight"):
        p = f"transformer.h.{layer_idx}"
    params = {}
    for ln in ("input_layernorm", "post_attention_layernorm"):
        params[ln] = _t(reader, f"{p}.{ln}.weight", dtype)
        params[f"{ln}_bias"] = _t(reader, f"{p}.{ln}.bias", dtype)
    # fused qkv: rows ordered per-head [q(hd), k(hd), v(hd)]
    w = _t(reader, f"{p}.self_attention.query_key_value.weight", dtype)
    b = _t(reader, f"{p}.self_attention.query_key_value.bias", dtype)
    d = w.shape[1]
    n_head = reader.config.get("n_head") or reader.config.get(
        "num_attention_heads"
    )
    head_dim = d // n_head
    w4 = w.reshape(n_head, 3, head_dim, d)
    b4 = b.reshape(n_head, 3, head_dim)
    params["q_proj"] = w4[:, 0].reshape(n_head * head_dim, d).T
    params["k_proj"] = w4[:, 1].reshape(n_head * head_dim, d).T
    params["v_proj"] = w4[:, 2].reshape(n_head * head_dim, d).T
    params["q_bias"] = b4[:, 0].reshape(-1)
    params["k_bias"] = b4[:, 1].reshape(-1)
    params["v_bias"] = b4[:, 2].reshape(-1)
    params["o_proj"] = _t(reader, f"{p}.self_attention.dense.weight", dtype).T
    params["o_bias"] = _t(reader, f"{p}.self_attention.dense.bias", dtype)
    params["up_proj"] = _t(reader, f"{p}.mlp.dense_h_to_4h.weight", dtype).T
    params["up_bias"] = _t(reader, f"{p}.mlp.dense_h_to_4h.bias", dtype)
    params["down_proj"] = _t(reader, f"{p}.mlp.dense_4h_to_h.weight", dtype).T
    params["down_bias"] = _t(reader, f"{p}.mlp.dense_4h_to_h.bias", dtype)
    return params


def _load_client(reader, dtype=None) -> dict:
    pref = "" if reader.has("word_embeddings.weight") else "transformer."
    out = {
        "embed": _t(reader, f"{pref}word_embeddings.weight", dtype),
        "embed_norm": _t(
            reader, f"{pref}word_embeddings_layernorm.weight", dtype
        ),
        "embed_norm_bias": _t(
            reader, f"{pref}word_embeddings_layernorm.bias", dtype
        ),
        "norm": _t(reader, f"{pref}ln_f.weight", dtype),
        "norm_bias": _t(reader, f"{pref}ln_f.bias", dtype),
    }
    out["lm_head"] = out["embed"].T  # tied
    return out


register_family(
    Family(
        "bloom", bloom_spec_from_hf, loader=_load_block,
        client_loader=_load_client,
    )
)
