// Byte-plane split/merge for 2-byte dtypes (bf16/fp16 wire compression).
//
// The hot transform of the lossless wire wrapper: separating the two byte
// planes of little-endian 2-byte elements before compression (the exponent
// plane is highly redundant). Native so the wire path doesn't pay numpy
// temporary allocations on multi-GB tensors. Built lazily by
// bloombee_tpu/native/__init__.py with g++ -O3 -shared; the Python caller
// falls back to numpy when no toolchain is available.
//
// Capability port of the reference's byte_split layout
// (/root/reference/src/bloombee/utils/lossless_transport.py).

#include <cstddef>
#include <cstdint>

extern "C" {

// src: n 2-byte elements; dst: plane0 (low bytes) then plane1 (high bytes)
void byte_split_2(const uint8_t* src, uint8_t* dst, size_t n) {
  uint8_t* lo = dst;
  uint8_t* hi = dst + n;
  for (size_t i = 0; i < n; ++i) {
    lo[i] = src[2 * i];
    hi[i] = src[2 * i + 1];
  }
}

// inverse: planes back to interleaved pairs
void byte_merge_2(const uint8_t* src, uint8_t* dst, size_t n) {
  const uint8_t* lo = src;
  const uint8_t* hi = src + n;
  for (size_t i = 0; i < n; ++i) {
    dst[2 * i] = lo[i];
    dst[2 * i + 1] = hi[i];
  }
}

}  // extern "C"
