"""Scripted fault schedules (PR 13): "at decode step N, do X".

The probabilistic chaos plan answers "does the swarm survive random
abuse?"; a FaultSchedule answers the sharper question "after THIS fault
at THIS step, does EXACTLY this recovery sequence run?". Unit tests pin
the step-counting contract (span-output replies only, per-entry counters,
port filters, exactly-once firing, ledger records); the e2e scripts a
hard server crash at decode step 4 and requires crash -> standby
promotion -> client reroute+replay with the final generation
token-identical to HF greedy — zero hard failures, run after run.
"""

import asyncio
import types

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.utils import ledger
from bloombee_tpu.wire import faults, tensor_codec
from bloombee_tpu.wire.faults import (
    FaultPlan,
    FaultSchedule,
    InjectedFault,
    ScheduledFault,
)


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_sched")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _span_output_frame(arr=None):
    """A frame shaped like a server step reply: "sitem" with tensor metas
    and compute timing in the meta — the swarm's logical clock tick."""
    if arr is None:
        arr = np.ones((1, 2, 4), np.float32)
    m, b = tensor_codec.serialize_tensor(arr, compression=True)
    header = {
        "t": "sitem", "id": 7,
        "meta": {"t_compute_ms": 1.0},
        "tm": [m.to_wire()],
    }
    return header, [b]


def _conn(port=7000):
    return types.SimpleNamespace(peer=("127.0.0.1", port))


# ------------------------------------------------------- step counting
def test_schedule_counts_only_span_output_replies():
    """Control frames (acks, opens, client requests) must not tick the
    step counter — only span-output replies are decode steps."""
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=2, action="drop")]
    ))

    async def run():
        # non-step frames: no tensor metas / no compute stamp
        for header in (
            {"t": "open", "m": "rpc_inference"},
            {"t": "sitem", "id": 1, "meta": {}},  # ack: no tm
            {"t": "sitem", "id": 2, "meta": {"t_compute_ms": 1.0}},  # no tm
        ):
            assert await plan.on_send(_conn(), header, None) is None
        assert plan.schedule.pending()

        h1, b1 = _span_output_frame()
        assert await plan.on_send(_conn(), h1, b1) is None  # step 1
        h2, b2 = _span_output_frame()
        assert await plan.on_send(_conn(), h2, b2) == "drop"  # step 2: due

    asyncio.run(run())
    assert plan.schedule.log == [(2, "drop", None)]
    assert [(s, a) for s, a, _ in plan.log] == [("send", "scheduled.drop")]


def test_schedule_fires_exactly_once():
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=1, action="drop")]
    ))

    async def run():
        h, b = _span_output_frame()
        assert await plan.on_send(_conn(), h, b) == "drop"
        for _ in range(5):  # fired entries never re-fire
            h, b = _span_output_frame()
            assert await plan.on_send(_conn(), h, b) is None

    asyncio.run(run())
    assert len(plan.schedule.log) == 1
    assert not plan.schedule.pending()


def test_schedule_port_filters_tick_independently():
    """Two entries with different port filters each count only their own
    peer's replies — step 2 on port A is independent of steps on port B."""
    sched = FaultSchedule([
        ScheduledFault(at_step=2, action="drop", port=7001),
        ScheduledFault(at_step=1, action="drop", port=7002),
    ])
    plan = FaultPlan(schedule=sched)

    async def run():
        h, b = _span_output_frame()
        assert await plan.on_send(_conn(7001), h, b) is None  # A step 1
        h, b = _span_output_frame()
        assert await plan.on_send(_conn(7002), h, b) == "drop"  # B step 1
        h, b = _span_output_frame()
        assert await plan.on_send(_conn(7001), h, b) == "drop"  # A step 2

    asyncio.run(run())
    assert sched.log == [(1, "drop", 7002), (2, "drop", 7001)]


def test_schedule_counts_at_one_site_only():
    """In-proc swarms share one plan between client and server conns; a
    reply seen at send AND read must tick the counter once, not twice —
    so a site="send" schedule ignores on_read entirely."""
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=1, action="drop")], site="send"
    ))

    async def run():
        h, _ = _span_output_frame()
        assert await plan.on_read(_conn(), h) is None  # read: not counted
        assert plan.schedule.pending()
        h, b = _span_output_frame()
        assert await plan.on_send(_conn(), h, b) == "drop"

    asyncio.run(run())


def test_scheduled_corrupt_mutates_frame_and_ledgers():
    arr = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(1, 2, 4)
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=1, action="corrupt")]
    ))
    ledger.reset()

    async def run():
        header, blobs = _span_output_frame(arr)
        assert await plan.on_send(_conn(), header, blobs) is None
        meta = tensor_codec.TensorMeta.from_wire(header["tm"][0])
        return tensor_codec.deserialize_tensor(meta, blobs[0])

    out = asyncio.run(run())
    assert not np.array_equal(out, arr)  # the numbers lie...
    assert out.shape == arr.shape  # ...but the frame stays well-formed
    assert ledger.snapshot()["faults"] == {"wire.scheduled.corrupt": 1}


def test_scheduled_reset_kills_connection_loudly():
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=1, action="reset")]
    ))
    conn = _conn()
    conn.writer = types.SimpleNamespace(
        transport=None, close=lambda: None
    )

    async def run():
        h, b = _span_output_frame()
        with pytest.raises(InjectedFault):
            await plan.on_send(conn, h, b)

    asyncio.run(run())


def test_scheduled_crash_requires_bound_callback():
    plan = FaultPlan(schedule=FaultSchedule(
        [ScheduledFault(at_step=1, action="crash", target="primary")]
    ))

    async def run():
        h, b = _span_output_frame()
        with pytest.raises(RuntimeError, match="bound callback"):
            await plan.on_send(_conn(), h, b)

    asyncio.run(run())


def test_scheduled_crash_runs_callback_and_drops_reply():
    crashed = []
    sched = FaultSchedule(
        [ScheduledFault(at_step=1, action="crash", target="primary")]
    ).bind_crash("primary", lambda: crashed.append(True))
    plan = FaultPlan(schedule=sched)

    async def run():
        h, b = _span_output_frame()
        # the in-flight reply dies with the server, like a mid-step kill -9
        assert await plan.on_send(_conn(), h, b) == "drop"

    asyncio.run(run())
    assert crashed == [True]


# ------------------------------------------------------------ env knob
def test_schedule_from_env_parses_and_arms_plan(monkeypatch):
    monkeypatch.setenv("BBTPU_CHAOS_SCHEDULE", "3:reset; 7:partition:7711")
    monkeypatch.delenv("BBTPU_CHAOS", raising=False)
    plan = FaultPlan.from_env()  # schedule alone arms the plan
    assert plan is not None and plan.rules == []
    got = [(f.at_step, f.action, f.port) for f in plan.schedule.faults]
    assert got == [(3, "reset", None), (7, "partition", 7711)]


def test_schedule_from_env_rejects_crash(monkeypatch):
    monkeypatch.setenv("BBTPU_CHAOS_SCHEDULE", "2:crash")
    with pytest.raises(ValueError, match="crash"):
        FaultSchedule.from_env()


def test_schedule_from_env_rejects_malformed_entry(monkeypatch):
    monkeypatch.setenv("BBTPU_CHAOS_SCHEDULE", "5")
    with pytest.raises(ValueError, match="STEP:ACTION"):
        FaultSchedule.from_env()


# ------------------------------------------------------------------ e2e
def test_scripted_crash_at_step_4_recovers_token_identical(tiny_model_dir):
    """The acceptance scenario: script a hard primary crash at decode
    step 4 (no drain, no park, KV and sessions lost, registry record left
    to expire) and require the exact recovery sequence — standby
    promotion on advert silence, client reroute + history replay — with
    the final generation token-identical to HF greedy. No hard failures:
    the client API never surfaces the crash."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        primary = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.3,
        )
        standby = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.3, standby=True,
            promote_high_ms=500.0, promote_low_ms=100.0,
            promote_sustain_s=0.3, promote_jitter_s=0.4,
            drain_timeout=2.0,
        )
        await primary.start()
        await standby.start()

        ledger.reset()
        schedule = FaultSchedule([
            ScheduledFault(at_step=4, action="crash", target="primary"),
        ]).bind_crash("primary", primary.crash)
        faults.set_plan(FaultPlan(schedule=schedule))

        # the retry budget must outlast promotion latency (record expiry
        # 0.75s + sustain 0.3s + jitter <=0.4s + announce ticks): each
        # _recover attempt sleeps up to 1s, so 30 attempts is ~27s of
        # self-heal window; short ban + fast view refresh keep the client
        # probing instead of camping on the dead primary's stale record
        cfg = ClientConfig(
            max_retries=30, update_period=0.5,
            ban_timeout=0.5, ban_max=2.0,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg,
        )
        rng = np.random.default_rng(5)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        ids = await model.generate(
            input_ids, max_new_tokens=8, server_decode=False
        )
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=8,
                do_sample=False, use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)

        # the crash really was a crash, and it fired exactly where scripted
        assert primary._crashed
        assert schedule.log == [(4, "crash", "primary")]
        assert not schedule.pending()
        assert standby.promotions >= 1 and standby._promoted

        # ...and the ledger proves the full fault->recovery chain ran
        snap = ledger.snapshot()
        assert snap["faults"].get("server.crash") == 1
        assert snap["recoveries"].get("server.promotion", 0) >= 1
        assert snap["recoveries"].get("client.reroute_replay", 0) >= 1

        faults.set_plan(None)
        # primary died hard — only the survivors get a graceful stop
        await standby.stop()
        await reg.stop()

    asyncio.run(run())
