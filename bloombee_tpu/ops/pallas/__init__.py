"""Pallas TPU kernels for the hot ops.

The serving decode path is weight-bandwidth-bound and well served by XLA
fusion; these kernels target the places XLA's default lowering materializes
large intermediates — full [B, H, T, S] attention logits in HBM during
prefill / training. `flash_attention` streams K/V blocks through VMEM with
online-softmax accumulation instead.
"""

from bloombee_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
