"""Weight-only quantization for serving spans (int8 / int4).

The weight half of the reference's compression lever
(/root/reference/src/bloombee/flexgen_utils/compression.py:22-210 compresses
weights as well as KV). Decode is weight-bandwidth-bound — the span step
reads every projection matrix once per token — so storing projections as
int8 (or group-wise int4) halves (quarters) the HBM bytes per step and
raises the decode-throughput roofline accordingly. Compute stays bf16: the
dequantize (convert + scale multiply) is an elementwise producer that XLA
fuses into the matmul's operand read on TPU, so the dequantized matrix is
never materialized in HBM.

Layouts:
- int8: per-output-column symmetric scale. codes [..., in, out] int8,
  scale [..., 1, out].
- int4: group-wise (GROUP=32 x out) asymmetric — same group size as the
  int4 KV slab; round-to-nearest at larger groups is too noisy — two
  values packed per byte along the input dim. codes [..., in/2, out]
  uint8, scale/zero [..., in/GROUP, out] f16 (0.625 B/weight vs 2 B bf16,
  3.2x).

`QuantWeight` is a pytree: quantized leaves stack, scan, and donate through
the span step exactly like dense arrays.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

GROUP = 32

# 2D projection keys eligible for quantization (per-layer params dict);
# norms/biases/router stay dense — tiny, and precision-critical
QUANT_KEYS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
    "experts_gate", "experts_up", "experts_down",
)


class QuantWeight(NamedTuple):
    codes: jax.Array
    scale: jax.Array
    zero: jax.Array | None = None  # int4 only

    @property
    def bits(self) -> int:
        return 8 if self.codes.dtype == jnp.int8 else 4


def quantize_weight(w: jax.Array, bits: int = 8) -> QuantWeight:
    """Quantize [..., in, out] along the input (contraction) dim."""
    w = w.astype(jnp.float32)
    if bits == 8:
        amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)  # [..., 1, out]
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        codes = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return QuantWeight(codes=codes, scale=scale.astype(jnp.float32))
    if bits == 4:
        *lead, din, dout = w.shape
        gs = min(GROUP, din)
        if din % gs or din % 2:
            raise ValueError(f"in dim {din} not int4-groupable")
        g = din // gs
        wg = w.reshape(*lead, g, gs, dout)
        mn = wg.min(axis=-2, keepdims=True)  # [..., g, 1, out]
        mx = wg.max(axis=-2, keepdims=True)
        scale = (mx - mn) / 15.0
        safe = jnp.where(scale > 0, scale, 1.0)
        q = jnp.clip(jnp.round((wg - mn) / safe), 0, 15).astype(jnp.uint8)
        q = q.reshape(*lead, din, dout)
        codes = q[..., 0::2, :] | (q[..., 1::2, :] << 4)
        return QuantWeight(
            codes=codes,
            scale=scale.squeeze(-2).astype(jnp.float16),
            zero=mn.squeeze(-2).astype(jnp.float16),
        )
    raise ValueError(f"unsupported weight bits {bits}")


def dequantize_weight(qw: QuantWeight, dtype=jnp.bfloat16) -> jax.Array:
    if qw.bits == 8:
        return (qw.codes.astype(jnp.float32) * qw.scale).astype(dtype)
    codes = qw.codes
    lo = (codes & 0xF).astype(jnp.float32)
    hi = (codes >> 4).astype(jnp.float32)
    *lead, half, dout = codes.shape
    q = jnp.stack([lo, hi], axis=-2).reshape(*lead, half * 2, dout)
    din = half * 2
    gs = min(GROUP, din)
    g = din // gs
    qg = q.reshape(*lead, g, gs, dout)
    out = (
        qg * qw.scale[..., :, None, :].astype(jnp.float32)
        + qw.zero[..., :, None, :].astype(jnp.float32)
    )
    return out.reshape(*lead, din, dout).astype(dtype)


def maybe_dequantize(w, dtype=jnp.bfloat16):
    """Dense passthrough or fused-dequant entry used by the layer body."""
    if isinstance(w, QuantWeight):
        return dequantize_weight(w, dtype)
    return w


def quantize_span_params(stacked: dict, bits: int) -> dict:
    """Quantize the eligible 2D projections of a stacked span params dict
    (leaves carry a leading L dim). Returns a new dict; ineligible leaves
    (norms, biases, router) pass through dense."""
    out = {}
    for key, leaf in stacked.items():
        if key in QUANT_KEYS and getattr(leaf, "ndim", 0) >= 3:
            out[key] = quantize_weight(leaf, bits)
        else:
            out[key] = leaf
    return out


def quantize_layer_params(params: dict, bits: int) -> dict:
    """Per-layer (unstacked) variant of quantize_span_params: quantize via
    a transient 1-stack so the stacked-ndim eligibility gate applies
    unchanged — the shared idiom for hetero spans, offloaded host tails,
    and per-layer checkpoint loading."""
    import jax

    from bloombee_tpu.utils.tree import stack_params

    one = quantize_span_params(stack_params([params]), bits)
    return jax.tree.map(lambda x: x[0], one)


def params_nbytes(stacked: dict) -> int:
    from bloombee_tpu.utils.memory import tree_nbytes

    return tree_nbytes(stacked)
