"""bbtpu-lint core: file loading, suppressions, baseline, and the runner.

Eight PRs in, the hard bugs in this repro are protocol-discipline bugs —
speculative-write/commit/rollback pairing, lock discipline around device
dispatch, wire-field version filtering, the env.declare registry — none of
which ruff can see. This package is an AST-based checker with project-
specific rules (BB0xx codes, bloombee_tpu/analysis/rules.py) that encode
those invariants so they are enforced by CI instead of by memory.

Mechanics (all pure stdlib — the lint itself must never import jax):

- suppressions: ``# bbtpu: noqa[BB001]`` (or ``noqa[BB001,BB005]``, or a
  bare ``noqa`` for every code) on any physical line of the flagged
  statement silences that finding. Suppressions are for sites where the
  invariant is deliberately delegated (e.g. a speculative step whose
  rollback is owned by the calling stream driver) — the comment next to
  the noqa must say who owns it.
- baseline: a committed file of finding fingerprints
  (bloombee_tpu/analysis/baseline.txt). Findings in the baseline don't
  fail the gate; NEW findings do. Fingerprints hash the stripped source
  line (not the line number), so unrelated edits above a baselined
  finding don't invalidate it. ``--update-baseline`` rewrites the file
  from the current tree.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from pathlib import Path

NOQA_RE = re.compile(
    r"#\s*bbtpu:\s*noqa(?:\s*\[\s*([A-Z0-9_,\s]+?)\s*\])?",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str  # rule id, e.g. "BB001"
    path: str  # repo-relative posix path
    line: int  # 1-based line of the offending node
    message: str
    snippet: str = ""  # stripped source line (fingerprint input)
    # call chain for interprocedural findings (display names, caller
    # first). Not part of the fingerprint: a baselined finding survives
    # an unrelated refactor of an intermediate helper's name only if its
    # own site is untouched — which is the same contract as `snippet`.
    chain: tuple[str, ...] = ()

    def fingerprint(self) -> str:
        """Line-number-independent identity: a baselined finding survives
        edits elsewhere in the file but is invalidated the moment its own
        line changes (which is when a human should re-look at it)."""
        h = hashlib.sha1(
            f"{self.path}::{self.code}::{self.snippet}".encode()
        ).hexdigest()
        return h[:12]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class SourceFile:
    """One parsed file plus its suppression map."""

    def __init__(self, path: str, text: str):
        self.path = path  # repo-relative posix
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # lineno -> set of suppressed codes (None = every code)
        self.noqa: dict[int, set[str] | None] = {}
        for i, line in enumerate(self.lines, start=1):
            m = NOQA_RE.search(line)
            if not m:
                continue
            codes = m.group(1)
            if codes is None:
                self.noqa[i] = None
            else:
                self.noqa[i] = {
                    c.strip() for c in codes.split(",") if c.strip()
                }

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, code: str, lineno: int, end_lineno: int) -> bool:
        for ln in range(lineno, (end_lineno or lineno) + 1):
            codes = self.noqa.get(ln, "missing")
            if codes is None:
                return True
            if codes != "missing" and code in codes:
                return True
        return False

    def finding(
        self,
        code: str,
        node: ast.AST,
        message: str,
        chain: tuple[str, ...] = (),
    ):
        """Build a Finding for `node`, honoring noqa. Returns None when
        the site is suppressed."""
        lineno = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", lineno) or lineno
        if self.suppressed(code, lineno, end):
            return None
        return Finding(
            code=code,
            path=self.path,
            line=lineno,
            message=message,
            snippet=self.line_text(lineno),
            chain=chain,
        )


def iter_py_files(root: Path, paths: list[str]) -> list[Path]:
    """Expand CLI path arguments into .py files (sorted, deduped)."""
    out: set[Path] = set()
    for p in paths:
        fp = (root / p).resolve() if not Path(p).is_absolute() else Path(p)
        if fp.is_dir():
            out.update(fp.rglob("*.py"))
        elif fp.suffix == ".py" and fp.exists():
            out.add(fp)
    return sorted(out)


def load_source_files(
    root: Path, paths: list[str]
) -> tuple[list[SourceFile], list[Finding]]:
    """Parse every target file; unparsable files become findings instead
    of crashing the gate (ruff owns syntax, but a half-written file must
    not make the invariant gate vacuously pass)."""
    files: list[SourceFile] = []
    errors: list[Finding] = []
    for fp in iter_py_files(root, paths):
        rel = fp.relative_to(root).as_posix() if fp.is_relative_to(
            root
        ) else fp.as_posix()
        text = fp.read_text(encoding="utf-8")
        try:
            files.append(SourceFile(rel, text))
        except SyntaxError as e:
            errors.append(
                Finding(
                    code="BB000",
                    path=rel,
                    line=int(e.lineno or 1),
                    message=f"file does not parse: {e.msg}",
                    snippet="",
                )
            )
    return files, errors


# ------------------------------------------------------------------ baseline
def load_baseline(path: Path) -> set[str]:
    """Fingerprint set from a baseline file. Lines are
    ``<fingerprint>  # free-text comment``; blank lines and pure-comment
    lines are ignored, so an 'empty-or-commented' baseline stays legal."""
    if not path.exists():
        return set()
    fps: set[str] = set()
    for raw in path.read_text(encoding="utf-8").splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        fps.add(line.split()[0])
    return fps


def write_baseline(path: Path, findings: list[Finding]) -> None:
    """Rewrite the baseline from the current findings, one commented line
    per entry so a reviewer can see WHAT was baselined without chasing
    fingerprints."""
    lines = [
        "# bbtpu-lint baseline — accepted legacy findings.",
        "# Regenerate with: scripts/analyze.sh --update-baseline",
        "# Every entry MUST carry a justification comment; prefer an",
        "# inline `# bbtpu: noqa[BBxxx]` (visible at the site) for",
        "# deliberate invariant delegations and keep this file for",
        "# legacy findings awaiting a real fix.",
        "",
    ]
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        lines.append(f"{f.fingerprint()}  # {f.render()}")
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")


# -------------------------------------------------------------------- runner
def run_rules(
    files: list[SourceFile], rules: list
) -> list[Finding]:
    """Call-graph prepare (interprocedural rules), per-file pass, then
    cross-file finalize (BB004/BB006 correlate declarations in one file
    with surfacing in another)."""
    needs_graph = [r for r in rules if hasattr(r, "prepare")]
    if needs_graph:
        from bloombee_tpu.analysis.callgraph import CallGraph

        graph = CallGraph(files)
        for rule in needs_graph:
            rule.prepare(files, graph)
    findings: list[Finding] = []
    for rule in rules:
        for sf in files:
            findings.extend(rule.visit_file(sf))
    for rule in rules:
        findings.extend(rule.finalize())
    return findings


def analyze_source(
    sources: dict[str, str], rules: list | None = None
) -> list[Finding]:
    """Run rules over in-memory sources ({relpath: text}) — the fixture
    entry point tests/test_analysis.py drives."""
    from bloombee_tpu.analysis.rules import make_rules

    files = [SourceFile(p, t) for p, t in sources.items()]
    return run_rules(files, make_rules() if rules is None else rules)
