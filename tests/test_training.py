"""Remote training path: rpc_forward/rpc_backward gradients + p-tuning.

Ports the intent of /root/reference/tests/test_remote_sequential.py (remote
fwd/bwd grads vs local) and the ptune training loop. The remote chain's
input gradient must match a fully-local jax computation of the same
function, and p-tuning must reduce the loss.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.trainer import PTuneTrainer, RemoteSpanChain
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path_factory.mktemp("train") / "model")
    model.save_pretrained(d, safe_serialization=True)
    return d, config


def test_remote_backward_matches_local(env):
    d, config = env

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=64, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=64, page_size=4),
        ]
        for s in servers:
            await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        chain = RemoteSpanChain(model.manager)

        rng = np.random.default_rng(0)
        h_in = rng.normal(size=(2, 6, 64)).astype(np.float32)
        g_out = rng.normal(size=(2, 6, 64)).astype(np.float32)

        out, ctx = await chain.forward(h_in)
        g_in = await chain.backward(ctx, g_out)

        # local reference: same dense span function over ALL blocks
        from bloombee_tpu.models.checkpoint import load_span_params
        from bloombee_tpu.runtime.training import TrainingExecutor

        params, spec = load_span_params(d, 0, 3, dtype=jnp.float32)
        tex = TrainingExecutor(params, spec)
        ref_out = tex.forward(h_in)
        np.testing.assert_allclose(out, ref_out, atol=1e-4, rtol=1e-4)

        def f(h):
            from bloombee_tpu.runtime.training import (
                _train_plan,
                span_train_forward,
            )

            plan = jnp.asarray(_train_plan(2, 6, 3))
            return span_train_forward(params, h, plan, spec=spec)

        _, vjp = jax.vjp(f, jnp.asarray(h_in))
        (ref_g,) = vjp(jnp.asarray(g_out))
        np.testing.assert_allclose(
            g_in, np.asarray(ref_g), atol=1e-4, rtol=1e-4
        )

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_ptune_loss_decreases(env):
    d, config = env

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                             registry=RegistryClient("127.0.0.1", reg.port),
                             compute_dtype=jnp.float32, num_pages=64,
                             page_size=4)
        await server.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        trainer = PTuneTrainer(model, n_prompt=4, lr=0.2)

        rng = np.random.default_rng(1)
        ids = rng.integers(0, config.vocab_size, size=(2, 7))
        input_ids, target_ids = ids[:, :-1], ids[:, 1:]

        losses = [
            await trainer.train_step(input_ids, target_ids) for _ in range(6)
        ]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses

        await server.stop()
        await reg.stop()

    asyncio.run(run())


def test_deep_ptune_grads_match_local(env):
    """Deep per-layer prompts (reference ptune.py deep mode): the 2-server
    chain's prompt gradients must match one local VJP over all layers."""
    d, config = env

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=64, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=64, page_size=4),
        ]
        for s in servers:
            await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        chain = RemoteSpanChain(model.manager)

        rng = np.random.default_rng(0)
        h_in = rng.normal(size=(2, 6, 64)).astype(np.float32)
        g_out = rng.normal(size=(2, 6, 64)).astype(np.float32)
        deep = rng.normal(size=(3, 2, 64)).astype(np.float32) * 0.02

        out, ctx = await chain.forward(h_in, deep_prompts=deep)
        g_in, g_deep = await chain.backward(
            ctx, g_out, deep_prompts=deep
        )

        # local reference: all 3 layers in one span
        from bloombee_tpu.models.checkpoint import load_span_params
        from bloombee_tpu.runtime.training import (
            _train_plan,
            span_train_backward,
            span_train_forward,
        )

        params, spec = load_span_params(d, 0, 3, dtype=jnp.float32)
        plan = jnp.asarray(_train_plan(2, 6, 3))
        ref_out = span_train_forward(
            params, jnp.asarray(h_in), plan, jnp.asarray(deep), spec=spec
        )
        _, ref_g_in, ref_g_deep = span_train_backward(
            params, jnp.asarray(h_in), jnp.asarray(g_out), plan,
            jnp.asarray(deep), spec=spec,
        )
        np.testing.assert_allclose(out, np.asarray(ref_out), atol=1e-4,
                                   rtol=1e-4)
        np.testing.assert_allclose(g_in, np.asarray(ref_g_in), atol=1e-4,
                                   rtol=1e-4)
        np.testing.assert_allclose(g_deep, np.asarray(ref_g_deep),
                                   atol=1e-4, rtol=1e-4)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_deep_ptune_loss_decreases(env):
    d, config = env

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=64, page_size=4)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        trainer = PTuneTrainer(model, n_prompt=4, lr=0.1, deep=True)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, config.vocab_size, size=(2, 6))
        tgt = rng.integers(0, config.vocab_size, size=(2, 6))
        losses = [await trainer.train_step(ids, tgt) for _ in range(6)]
        assert losses[-1] < losses[0], losses
        assert np.abs(trainer.deep_prompts).sum() > 0  # actually trained
        await s.stop()
        await reg.stop()

    asyncio.run(run())
