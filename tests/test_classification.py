"""Sequence classification over a live swarm: fit a toy task.

Reference parity target: DistributedLlamaForSequenceClassification
(/root/reference/src/bloombee/models/llama/model.py:263) — remote frozen
blocks, local trainable score head on the last non-pad token.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.classification import (
    DistributedModelForSequenceClassification,
)
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=2,
        vocab_size=64,
        max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    torch.manual_seed(13)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_cls")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), config


def _toy_batch(rng, b, s, vocab):
    """Label = whether the LAST token id is in the top half of the vocab —
    linearly recoverable from the last token's hidden state, so the frozen
    chain + linear score head can fit it."""
    ids = rng.integers(0, vocab, size=(b, s))
    labels = (ids[:, -1] >= vocab // 2).astype(np.int32)
    return ids, labels


def test_swarm_classification_fits_toy_task(tiny_model_dir):
    model_dir, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = BlockServer(
            model_uid="tiny", start=0, end=1, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        s2 = BlockServer(
            model_uid="tiny", start=1, end=2, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        await s1.start()
        await s2.start()

        clf = DistributedModelForSequenceClassification.from_pretrained(
            model_dir, rc(), num_labels=2, model_uid="tiny", lr=0.3,
        )
        rng = np.random.default_rng(0)
        first = None
        for step in range(200):
            ids, labels = _toy_batch(rng, 16, 5, config.vocab_size)
            loss = await clf.train_step(ids, labels)
            if first is None:
                first = loss
        ids, labels = _toy_batch(rng, 32, 5, config.vocab_size)
        preds = await clf.predict(ids)
        acc = float((preds == labels).mean())
        assert loss < first * 0.5, (first, loss)
        assert acc >= 0.8, acc

        # ragged batch via attention_mask: logits must come from each
        # row's LAST REAL token, so moving the pad boundary changes them
        ids, _ = _toy_batch(rng, 4, 6, config.vocab_size)
        mask = np.ones_like(ids)
        mask[:, 4:] = 0
        got = await clf.scores(ids, attention_mask=mask)
        want = await clf.scores(ids[:, :4])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_classification_with_prompt_tuning(tiny_model_dir):
    """n_prompt > 0 trains prompts through rpc_backward jointly with the
    score head; the task should fit at least as well."""
    model_dir, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        rc = RegistryClient("127.0.0.1", reg.port)
        s1 = BlockServer(
            model_uid="tiny", start=0, end=2, model_dir=model_dir,
            registry=rc, compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        await s1.start()

        clf = DistributedModelForSequenceClassification.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), num_labels=2,
            model_uid="tiny", lr=0.3, n_prompt=4,
        )
        p0 = np.asarray(clf.prompts).copy()
        rng = np.random.default_rng(1)
        first = None
        for _ in range(100):
            ids, labels = _toy_batch(rng, 16, 5, config.vocab_size)
            loss = await clf.train_step(ids, labels)
            if first is None:
                first = loss
        assert loss < first * 0.6, (first, loss)
        assert not np.allclose(p0, np.asarray(clf.prompts)), (
            "prompts never trained"
        )

        await s1.stop()
        await reg.stop()

    asyncio.run(run())
