"""Streaming zero-copy wire path: off-loop codec pipeline, vectored
framing, negotiated compression.

Covers the PR-18 wire rebuild: the codec matrix across every wire dtype x
{raw, zstd, zlib} x byte-split (including the pure-numpy fallback when
the native byte_split_lib is absent), read-only zero-copy deserialize
views, lean-meta compat defaults, vectored frame buffers, per-connection
codec negotiation against new and legacy peers (both directions), stream
ordering under the off-loop pipeline, and codec-failure isolation. The
chaos-marked e2e at the bottom is the CODEC matrix entry's workload
(scripts/chaos.sh): a real swarm decode, every frame forced through the
codec pool, token-identical to HF greedy under seeded delay+reset faults.
"""

import asyncio
import struct

import ml_dtypes
import numpy as np
import pytest

import bloombee_tpu.native as native_mod
from bloombee_tpu.wire import faults, pipeline as pipeline_mod
from bloombee_tpu.wire.pipeline import CodecPipeline
from bloombee_tpu.wire.rpc import (
    RpcError,
    RpcServer,
    _encode_frame,
    _frame_buffers,
    connect,
)
from bloombee_tpu.wire.tensor_codec import (
    LEGACY_WIRE_CODECS,
    TensorMeta,
    deserialize_tensor,
    register_codec,
    serialize_tensor,
    supported_codecs,
    unregister_codec,
)

WIRE_DTYPES = [
    np.float32, np.float16, ml_dtypes.bfloat16, np.int32, np.int64,
    np.uint8, np.bool_, np.float64,
]


def _u8(arr):
    """Comparable view for dtypes numpy can't compare natively (bf16)."""
    return arr.view(np.uint8)


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


@pytest.fixture
def force_compression(monkeypatch):
    """Drop the size/gain gates so the matrix below exercises every codec
    on small arrays (the gates themselves are covered in test_wire.py)."""
    monkeypatch.setenv("BBTPU_MIN_COMPRESS_BYTES", "0")
    monkeypatch.setenv("BBTPU_MIN_COMPRESS_GAIN", "-1000000000")


# --------------------------------------------------- codec roundtrip matrix
@pytest.mark.parametrize("codec", ["raw", "zstd", "zlib"])
@pytest.mark.parametrize("dtype", WIRE_DTYPES)
def test_codec_roundtrip_matrix(dtype, codec, force_compression):
    """Every wire dtype through every built-in codec; 2-byte dtypes take
    the byte-split plane layout whenever a compressor is chosen."""
    if codec not in supported_codecs():
        pytest.skip(f"{codec} not available in this environment")
    rng = np.random.default_rng(5)
    arr = (rng.integers(0, 4, size=(7, 33)) * 3).astype(dtype)
    if codec == "raw":
        meta, payload = serialize_tensor(arr, compression=False)
        assert meta.codec == "raw" and not meta.byte_split
    else:
        meta, payload = serialize_tensor(arr, allowed=frozenset({codec}))
        assert meta.codec == codec
        assert meta.byte_split == (np.dtype(dtype).itemsize == 2)
    out = deserialize_tensor(meta, payload)
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(_u8(out), _u8(arr))


@pytest.mark.parametrize("dtype", [np.float16, ml_dtypes.bfloat16])
def test_byte_split_pure_numpy_fallback(dtype, force_compression,
                                        monkeypatch):
    """Without the native byte_split_lib the numpy plane transpose must
    produce the SAME wire bytes (the fallback is a layout contract, not a
    best-effort): payloads from either implementation cross-decode."""
    rng = np.random.default_rng(6)
    arr = rng.normal(size=(65, 17)).astype(dtype)
    meta_native, payload_native = serialize_tensor(
        arr, allowed=frozenset({"zlib"})
    )
    monkeypatch.setattr(native_mod, "byte_split_lib", lambda: None)
    meta_fb, payload_fb = serialize_tensor(arr, allowed=frozenset({"zlib"}))
    assert meta_fb.codec == "zlib" and meta_fb.byte_split
    assert bytes(payload_fb) == bytes(payload_native)
    # fallback decode of a (possibly native-encoded) payload
    out = deserialize_tensor(meta_native, payload_native)
    np.testing.assert_array_equal(_u8(out), _u8(arr))


def test_from_wire_lean_meta_defaults():
    """An older peer's lean meta (dtype+shape only) must not KeyError:
    absent codec means raw bytes, absent byte_split means off."""
    meta = TensorMeta.from_wire({"d": "f32", "s": [2, 3]})
    assert meta.codec == "raw" and meta.byte_split is False
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = deserialize_tensor(meta, arr.tobytes())
    np.testing.assert_array_equal(out, arr)


def test_deserialize_raw_is_readonly_zero_copy_view():
    """Raw-codec payloads come back as a read-only view over the receive
    buffer — no copy on the hot path; writable=True is the one path that
    still pays it."""
    arr = np.arange(64, dtype=np.float32)
    meta, payload = serialize_tensor(arr, compression=False)
    buf = memoryview(bytes(payload))
    out = deserialize_tensor(meta, buf)
    assert not out.flags.writeable
    assert np.shares_memory(out, np.frombuffer(buf, dtype=np.uint8))
    with pytest.raises((ValueError, RuntimeError)):
        out[0] = 1.0
    w = deserialize_tensor(meta, buf, writable=True)
    assert w.flags.writeable
    assert not np.shares_memory(w, np.frombuffer(buf, dtype=np.uint8))
    w[0] = -1.0  # mutating the copy never touches the receive buffer
    np.testing.assert_array_equal(out, arr)


# ------------------------------------------------------------ frame layout
def test_frame_buffers_vectored_layout_matches_encode_frame():
    """writelines ships _frame_buffers as-is: prefix+header first, then
    every blob object UNCOPIED, and the concatenation is byte-identical
    to the contiguous _encode_frame used by tests/tooling."""
    blobs = [memoryview(b"abcdef"), b"0123456789"]
    header = {"t": "sitem", "id": 7, "meta": {"x": 1}}
    bufs = _frame_buffers(header, blobs)
    assert bufs[1] is blobs[0] and bufs[2] is blobs[1]  # zero-copy payloads
    joined = b"".join(bytes(b) for b in bufs)
    assert joined == _encode_frame(header, blobs)
    total, header_len = struct.unpack("<II", joined[:8])
    assert len(joined) == 4 + total
    assert joined[8 + header_len:] == b"abcdef0123456789"


# ------------------------------------------------------- pipeline scheduling
class _CountingExecutor:
    """Real thread pool that counts submissions (observing the off-loop
    boundary without guessing at timings)."""

    def __init__(self):
        import concurrent.futures

        self.pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self.submits = 0

    def submit(self, fn, *args):
        self.submits += 1
        return self.pool.submit(fn, *args)


def test_pipeline_inline_threshold_skips_executor(monkeypatch):
    """Payloads under BBTPU_WIRE_PIPELINE_INLINE (de)serialize in-line —
    a thread hop costs more than codec work on tiny frames — while bigger
    ones go through the pool."""
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE", "1")
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE_INLINE", "256")
    counting = _CountingExecutor()
    monkeypatch.setattr(pipeline_mod, "codec_executor", lambda: counting)

    async def run():
        pipe = CodecPipeline()
        small = np.zeros(4, np.float32)  # 16 B
        big = np.zeros(4096, np.float32)  # 16 KiB
        await pipe.encode([small], compression=False)
        assert counting.submits == 0
        metas, blobs = await pipe.encode([big], compression=False)
        assert counting.submits == 1
        fut = pipe.decode_submit(
            [serialize_tensor(small, compression=False)[0].to_wire()],
            [small.tobytes()],
        )
        assert fut.done()  # inline decode resolves before any awaiting
        assert counting.submits == 1
        await pipe.decode_wait(metas, blobs)
        assert counting.submits == 2

    asyncio.run(run())
    counting.pool.shutdown()


def test_stream_ordering_under_forced_pipeline(monkeypatch):
    """Mixed-size items (some decoded off-loop, some inline, finishing at
    different times) must arrive in send order: the single drain task is
    the ordering guarantee, not decode completion order."""
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE", "1")
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE_INLINE", "0")
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE_DEPTH", "4")
    N = 40

    async def run():
        async def echo_stream(stream):
            while True:
                item = await stream.recv()
                if item is None:
                    break
                meta, tensors = item
                await stream.send({"seq": meta["seq"]}, [tensors[0]])
            await stream.close()

        server = RpcServer(
            stream_handlers={"s": echo_stream}, host="127.0.0.1"
        )
        await server.start()
        conn = await connect("127.0.0.1", server.port)
        stream = await conn.open_stream("s", {})
        rng = np.random.default_rng(11)
        sent = []
        for i in range(N):
            size = int(rng.choice([4, 64, 20000]))
            arr = rng.normal(size=(size,)).astype(np.float32)
            sent.append(arr)
            await stream.send({"seq": i}, [arr])
        await stream.close()
        got = []
        while True:
            item = await stream.recv()
            if item is None:
                break
            got.append(item)
        assert [m["seq"] for m, _ in got] == list(range(N))
        for (_, tensors), arr in zip(got, sent):
            np.testing.assert_array_equal(tensors[0], arr)
        stats = server.pipeline_stats()
        assert stats["enabled"] and stats["rx_jobs"] >= N
        assert conn.pipeline.stats()["tx_jobs"] >= N
        await conn.close()
        await server.stop()

    asyncio.run(run())


def test_codec_failure_fails_one_call_not_the_connection(monkeypatch):
    """A frame whose payload fails the codec (corruption, peer bug) kills
    that one call/stream — the other multiplexed users keep going."""
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE", "1")
    bad_meta = {"d": "f32", "s": [64], "c": "zstd", "b": False}

    async def run():
        async def echo(meta, tensors):
            return {"n": len(tensors)}, list(tensors)

        async def echo_stream(stream):
            while True:
                item = await stream.recv()
                if item is None:
                    break
                meta, tensors = item
                await stream.send({"seq": meta["seq"]}, list(tensors))
            await stream.close()

        server = RpcServer(
            unary_handlers={"echo": echo},
            stream_handlers={"s": echo_stream},
            host="127.0.0.1",
        )
        await server.start()
        conn = await connect("127.0.0.1", server.port)

        # unary with a garbage zstd payload: the server answers an err
        # frame (decode happens in the handler task, not the read loop)
        rid = next(conn._ids)
        fut = asyncio.get_running_loop().create_future()
        conn._pending[rid] = fut
        await conn._send(
            {"t": "req", "id": rid, "m": "echo", "meta": {},
             "tm": [bad_meta]},
            [b"not zstd at all"],
        )
        with pytest.raises(RpcError):
            await asyncio.wait_for(fut, 10.0)

        # a corrupt sitem fails only its stream (ordered drain path)
        stream = await conn.open_stream("s", {})
        server_conn = next(iter(server._conns))
        client_stream_on_server = None
        for _ in range(100):
            if server_conn._streams:
                client_stream_on_server = next(
                    iter(server_conn._streams.values())
                )
                break
            await asyncio.sleep(0.01)
        assert client_stream_on_server is not None
        await server_conn._send_payload(
            {"t": "sitem", "id": stream.id, "meta": {"seq": 0}}, None
        )
        # hand-corrupt: send a bad payload as if it were a stream item
        await server_conn._send(
            {"t": "sitem", "id": stream.id, "meta": {"seq": 1},
             "tm": [bad_meta]},
            [b"garbage"],
        )
        item = await stream.recv()  # the good item arrives first (ordered)
        assert item is not None and item[0]["seq"] == 0
        with pytest.raises(RpcError):
            await stream.recv()

        # the connection survived both: a normal call still answers
        meta, tensors = await conn.call(
            "echo", {}, [np.arange(4, dtype=np.float32)]
        )
        assert meta["n"] == 1
        np.testing.assert_array_equal(tensors[0], np.arange(4.0))

        await conn.close()
        await server.stop()

    asyncio.run(run())


# ---------------------------------------------------------- codec negotiation
@pytest.fixture
def test_codec():
    """A registered throwaway codec, preferred when negotiated; removed
    again afterwards so no other test sees it."""
    calls = {"c": 0, "d": 0}

    def compress(buf):
        calls["c"] += 1
        return b"T" + bytes(buf)

    def decompress(buf):
        calls["d"] += 1
        assert bytes(buf[:1]) == b"T"
        return bytes(buf[1:])

    register_codec("testc", compress, decompress, prefer=True)
    try:
        yield calls
    finally:
        unregister_codec("testc")


def test_supported_codecs_registry_and_allowlist(test_codec, monkeypatch):
    assert {"raw", "zlib", "testc"} <= supported_codecs()
    monkeypatch.setenv("BBTPU_WIRE_CODECS", "zlib")
    assert supported_codecs() == frozenset({"raw", "zlib"})  # raw always
    monkeypatch.setenv("BBTPU_WIRE_CODECS", "raw")
    assert supported_codecs() == frozenset({"raw"})


def test_unnegotiated_serialize_never_picks_registered_codec(
    test_codec, force_compression
):
    """allowed=None is the pre-negotiation contract: a registered codec —
    even a preferred one — must NOT leak into payloads for peers that
    never advertised it."""
    arr = np.zeros(4096, np.float32)
    meta, _ = serialize_tensor(arr)
    assert meta.codec in LEGACY_WIRE_CODECS
    assert test_codec["c"] == 0
    meta2, payload2 = serialize_tensor(
        arr, allowed=frozenset({"testc", "raw"})
    )
    assert meta2.codec == "testc" and test_codec["c"] == 1
    out = deserialize_tensor(meta2, payload2)
    np.testing.assert_array_equal(out, arr)


def _echo_server(**kw):
    async def echo(meta, tensors):
        return {"ok": True}, [np.ascontiguousarray(t) for t in tensors]

    return RpcServer(unary_handlers={"echo": echo}, host="127.0.0.1", **kw)


def test_negotiation_new_peers_adopt_registered_codec(
    test_codec, force_compression
):
    """new<->new: the codec advert rides the first frames each side sends,
    so the server's reply to the FIRST call — and everything after — uses
    the negotiated preferred codec. Values stay exact."""

    async def run():
        server = _echo_server()
        await server.start()
        conn = await connect("127.0.0.1", server.port)
        arr = np.arange(2048, dtype=np.float32)
        meta, tensors = await conn.call("echo", {"i": 0}, [arr])
        np.testing.assert_array_equal(tensors[0], arr)
        # the req frame carried our advert, so the reply already used the
        # negotiated codec; our request could not (no advert seen yet)
        assert test_codec["c"] >= 1 and test_codec["d"] >= 1
        before = test_codec["c"]
        meta, tensors = await conn.call("echo", {"i": 1}, [arr])
        np.testing.assert_array_equal(tensors[0], arr)
        # second request: the client has seen the server's advert too, so
        # BOTH directions now compress with the test codec
        assert test_codec["c"] >= before + 2
        assert conn.peer_codecs >= {"testc"}
        await conn.close()
        await server.stop()

    asyncio.run(run())


@pytest.mark.parametrize("legacy_side", ["server", "client"])
def test_negotiation_mixed_swarm_degrades_to_legacy(
    legacy_side, test_codec, force_compression
):
    """new<->old in both directions: a legacy peer never advertises (and
    ignores ours), so the registered codec must never appear on the wire
    — both sides fall back to the pre-negotiation contract byte-for-byte,
    and values stay exact."""

    async def run():
        server = _echo_server(legacy_wire=(legacy_side == "server"))
        await server.start()
        conn = await connect(
            "127.0.0.1", server.port,
            legacy_wire=(legacy_side == "client"),
        )
        arr = np.arange(2048, dtype=np.float32)
        for i in range(3):
            meta, tensors = await conn.call("echo", {"i": i}, [arr])
            np.testing.assert_array_equal(tensors[0], arr)
        assert test_codec["c"] == 0 and test_codec["d"] == 0
        if legacy_side == "server":
            # the client saw no advert: still assuming the legacy set
            assert conn.peer_codecs == LEGACY_WIRE_CODECS
            assert not next(iter(server._conns)).pipeline.enabled
        else:
            assert not conn.pipeline.enabled  # legacy emulation: sync codec
        await conn.close()
        await server.stop()

    asyncio.run(run())


# ------------------------------------------------------- chaos e2e (CODEC=1)
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        # 2 layers as two 1-layer spans: every server compiles the SAME
        # span shape, so the swarm pays one trace instead of two
        num_hidden_layers=2,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    # tiny configs default eos_token_id=2: HF greedy would stop the
    # moment argmax lands on token 2, truncating the reference while the
    # swarm generates all max_new_tokens — disable eos stopping so both
    # sides emit the same number of argmax tokens
    model.generation_config.eos_token_id = None
    d = tmp_path_factory.mktemp("tiny_llama_wire")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_decode_through_forced_codec_pool(tiny_model_dir, monkeypatch):
    # slow: runs inside tier-1 anyway via the chaos gate's CODEC matrix
    # entry (-m chaos) — the direct tier-1 pass skipping it avoids paying
    # the ~15s swarm twice per suite run
    """The CODEC matrix entry's workload: every frame forced through the
    off-loop codec pool (inline threshold 0), decode under seeded delay +
    reset + in-flight corruption faults with the integrity layer on and a
    reroute-capable swarm — tokens must equal the fault-free HF greedy
    reference, and the server must show pipelined frames actually
    flowed."""
    import jax.numpy as jnp
    import torch

    from bloombee_tpu.client.config import ClientConfig
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.wire.faults import (
        FaultPlan,
        FaultRule,
        _is_span_output_reply,
    )

    monkeypatch.setenv("BBTPU_WIRE_PIPELINE", "1")
    monkeypatch.setenv("BBTPU_WIRE_PIPELINE_INLINE", "0")
    model_dir, hf_model, config = tiny_model_dir

    def _server(registry, start, end, **kw):
        kw.setdefault("compute_dtype", jnp.float32)
        kw.setdefault("num_pages", 64)
        kw.setdefault("page_size", 4)
        kw.setdefault("integrity", True)  # stamp out_digest on replies
        return BlockServer(
            model_uid="tiny", start=start, end=end, model_dir=model_dir,
            registry=registry, **kw,
        )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        # every block gets a backup: the corrupt fault can land on ANY
        # span-output reply (head included), and an integrity reroute
        # with no alternate covering server would hang on ban expiry —
        # flaky under the chaos matrix's ambient jitter
        s_a = _server(rc(), 0, 1, throughput=10.0)
        s_b = _server(rc(), 1, 2, throughput=10.0)  # preferred tail
        s_c = _server(rc(), 1, 2, throughput=1.0)  # tail reroute target
        s_d = _server(rc(), 0, 1, throughput=1.0)  # head reroute target
        for s in (s_a, s_b, s_c, s_d):
            await s.start()

        input_ids = np.arange(5)[None, :] % config.vocab_size
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=6,
                do_sample=False, use_cache=True,
            ).numpy()

        # compose with any ambient chaos.sh plan instead of replacing it,
        # so the matrix entry's DELAY_P jitter stays live under this test
        plan = faults.get_plan() or FaultPlan(seed=13)
        # most-specific first: _pick returns the first matching rule
        plan.add(FaultRule(site="send", action="corrupt", method="sitem",
                           nth=1, count=1,
                           predicate=_is_span_output_reply))
        plan.add(FaultRule(site="send", action="reset", method="sitem",
                           port=s_b.port, nth=3, count=1))
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           port=s_a.port, delay_s=0.01, nth=1, count=4))
        faults.set_plan(plan)

        # the ban window must stay SHORTER than the recovery-retry horizon:
        # the matrix's ambient corruption can ban BOTH servers covering a
        # block at once, and recovery only succeeds once a ban lapses —
        # 2s bans against ~0.6s of retry backoff is a guaranteed flake
        cfg = ClientConfig(use_push=False, ban_timeout=0.25, ban_max=1.0,
                           max_retries=6, integrity=True)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(16, 1)
        await session.__aenter__()
        assert s_b.port in {
            sp.span.server_info.port for sp in session._spans
        }
        ids = await model.generate(input_ids, max_new_tokens=6,
                                   session=session)
        np.testing.assert_array_equal(ids, ref)
        # the pipelined path actually carried frames: probe while the
        # session is still open — after reroutes/close a server may hold
        # zero live conns, and stats()["enabled"] is an any() over them
        servers = (s_a, s_b, s_c, s_d)
        stats = [s.rpc.pipeline_stats() for s in servers]
        assert any(p["enabled"] for p in stats), stats
        assert sum(p["rx_jobs"] for p in stats) > 0, stats
        final_ports = {sp.span.server_info.port for sp in session._spans}
        await session.__aexit__(None, None, None)

        # the faults landed
        actions = {(site, act) for site, act, _ in plan.log}
        # the one legitimate excuse for an unfired reset: the matrix's
        # ambient corruption banned the preferred tail before its 3rd
        # send, so the session finished the decode on the reroute target
        # and the port-pinned rule had no traffic left to hit
        assert ("send", "reset") in actions or s_b.port not in final_ports
        assert ("send", "delay") in actions
        assert ("send", "corrupt") in actions
        # the corruption was CAUGHT (digest mismatch -> replay), not
        # silently decoded into the token stream
        assert session.integrity_reroutes >= 1

        faults.set_plan(None)
        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())
