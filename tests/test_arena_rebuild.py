"""Arena rebuild after a kernel failure consumed the donated buffers.

The recovery path can only ever fire after a real kernel failure, so it is
never exercised incidentally — these tests force one (round-4 verdict):

- manager level: epoch bookkeeping, parked sequences surviving a rebuild
  and unparking into the fresh arena with their data intact
- e2e: an injected kernel failure mid-generation consumes the arena; the
  pre-rebuild session's next step gets the typed `session_lost` reply, the
  client replays its token history onto the same (healthy, UNBANNED)
  server and the generation completes token-exact.

Reference analog: a CUDA error kills the reference's runtime process and
its supervisor restarts the whole container (server.py:524-541); here the
server survives and only the affected sessions replay.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


def make_manager(**kw):
    defaults = dict(
        num_layers=2, num_pages=8, page_size=4, n_kv_heads=1, head_dim=4,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return CacheManager(**defaults)


def test_rebuild_invalidates_resident_preserves_parked():
    async def run():
        m = make_manager()
        async with m.allocate(1, 8) as h_res, m.allocate(1, 8) as h_park:
            # write 3 tokens into each and commit
            for h in (h_res, h_park):
                slots = m.write_slots(h, 3, commit=True)
                val = float(h.handle_id + 1)
                m.arena["k"] = m.arena["k"].at[:, slots].set(val)
                m.arena["v"] = m.arena["v"].at[:, slots].set(val)
            m.park_sequence(h_park.seq_ids[0])
            epoch0 = m.arena_epoch

            m.rebuild_arena()

            assert m.arena_epoch == epoch0 + 1
            # resident handle: KV gone, epoch stale, table reset
            assert not m.epoch_valid(h_res)
            assert m.table.seq(h_res.seq_ids[0]).l_seq == 0
            # parked handle: survives, re-stamped to the new epoch
            assert m.epoch_valid(h_park)
            # unpark into the FRESH arena restores length and data
            m.ensure_resident(h_park)
            assert m.table.seq(h_park.seq_ids[0]).l_seq == 3
            lens = m.context_lens(h_park)
            assert int(lens[0]) == 3
            val = float(h_park.handle_id + 1)
            pt = m.page_table(h_park, 4)[0]
            page = int(pt[0])
            got = np.asarray(
                m.arena["k"][0, page * m.page_size : page * m.page_size + 3]
            )
            np.testing.assert_allclose(got, val)

    asyncio.run(run())


def test_rebuild_stale_across_two_epochs():
    """A seq parked through rebuild 1 but resident during rebuild 2 goes
    stale; the per-seq stamp must not resurrect it."""

    async def run():
        m = make_manager()
        async with m.allocate(1, 8) as h:
            m.write_slots(h, 2, commit=True)
            m.park_sequence(h.seq_ids[0])
            m.rebuild_arena()
            assert m.epoch_valid(h)
            m.ensure_resident(h)  # back on device
            m.rebuild_arena()
            assert not m.epoch_valid(h)

    asyncio.run(run())


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=2,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_rebuild")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def test_e2e_kernel_failure_rebuild_replay_no_ban(
    tiny_model_dir, monkeypatch
):
    """Inject a kernel failure that consumes the arena mid-generation:
    the server must rebuild, the session's next step must get the typed
    session_lost reply, and the client must replay WITHOUT banning the
    healthy server (single-server swarm: a ban would strand recovery
    until ban_timeout) — then finish with the same tokens as a clean run.
    """
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = BlockServer(
            model_uid="tiny", start=0, end=2, model_dir=model_dir,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await s1.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        rng = np.random.default_rng(2)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))

        # clean reference run first
        ref_ids = await model.generate(
            input_ids, max_new_tokens=6, server_decode=False
        )

        # arm the failure: the NEXT span step deletes the arena buffers
        # (as a mid-chain donation failure would) and raises — the
        # executor's except path must detect the consumed arena, rebuild,
        # and re-raise; the session's retry then sees session_lost
        from bloombee_tpu.runtime import executor as exec_mod

        real_step = exec_mod.span_step_packed
        state = {"armed": False, "fired": False}

        def exploding_step(*args, **kw):
            if state["armed"]:
                state["armed"] = False
                state["fired"] = True
                for a in jax.tree.leaves(
                    (s1.manager.arena["k"], s1.manager.arena["v"])
                ):
                    a.delete()
                raise RuntimeError("injected kernel failure (test)")
            return real_step(*args, **kw)

        monkeypatch.setattr(exec_mod, "span_step_packed", exploding_step)

        epoch0 = s1.manager.arena_epoch
        async with model.inference_session(16, 1) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            toks = [cur]
            state["armed"] = True  # next step blows up mid-chain
            for _ in range(5):
                out = await sess.step(
                    model.embed(cur[:, None]), ids=cur[:, None]
                )
                cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
                toks.append(cur)

        assert state["fired"], "injected failure never fired"
        assert s1.manager.arena_epoch == epoch0 + 1, "arena was not rebuilt"
        # the healthy server must NOT have been banned during recovery
        assert not model.manager._bans, (
            f"client banned a healthy server: {model.manager._bans}"
        )
        got = np.concatenate(
            [input_ids, np.stack(toks, axis=1)], axis=1
        )
        np.testing.assert_array_equal(got, ref_ids)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())
