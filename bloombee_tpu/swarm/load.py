"""Shared load-advert interpretation: one defensive translation from an
untrusted `ServerInfo.load` wire advert into a predicted queue delay.

Extracted from client/sequence_manager.py (PR 6) so that server-side
consumers — measured-load rebalancing in server/block_selection.py and
the standby-promotion watcher in server/block_server.py — apply the
EXACT same sanitization the client router does. Adverts are hostile
wire input everywhere; there must be one bounded, monotone,
staleness-discounted reading of them, not three.
"""

from __future__ import annotations

import math

from bloombee_tpu.utils import clock

LOAD_STALE_S = 30.0  # advert age at which the load term decays to zero
LOAD_DELAY_CAP_S = 10.0  # hard cap on the load term: a garbage/hostile
# advert can inflate only its OWN server's cost, and only this far
LOAD_SHED_PENALTY_S = 1.0  # an actively-shedding server would refuse new
# work anyway; make it about as unattractive as a missing-cache server
_QUEUE_DEPTH_COST_S = 0.05  # per queued task, a rough serialized-step cost


def _finite_pos(x) -> float:
    """Clamp an untrusted advert number to a finite value >= 0 (NaN, inf,
    negatives, non-numbers all collapse to 0 = 'no load evidence')."""
    try:
        v = float(x)
    except (TypeError, ValueError):
        return 0.0
    if not math.isfinite(v) or v < 0.0:
        return 0.0
    return v


def predicted_queue_delay_s(server_info, now: float | None = None) -> float:
    """Predicted extra queueing delay (seconds) at this server, derived
    from its live load advert. Properties every consumer depends on
    (enforced here, property-tested in tests/test_overload_routing.py):

    - always finite, >= 0, <= LOAD_DELAY_CAP_S: added to a positive edge
      cost, Dijkstra stays valid no matter what the advert claims;
    - monotone non-decreasing in reported load (delay/p95/queue depth), so
      a server cannot make itself MORE attractive by advertising load —
      the no-advert baseline (0) is the floor, meaning a malicious advert
      can only repel traffic from its own server, never capture it;
    - staleness-discounted: the term decays linearly to zero by
      LOAD_STALE_S of advert age (load["ts"], writer wall clock, falling
      back to the registry record's writer-stamped stored_at), so a dead
      server's last hot advert doesn't repel traffic forever and a stale
      cool advert doesn't attract a stampede.
    """
    load = getattr(server_info, "load", None)
    if not isinstance(load, dict):
        return 0.0
    if now is None:
        now = clock.now()
    ts = load.get("ts")
    if not isinstance(ts, (int, float)) or not math.isfinite(float(ts)):
        ts = getattr(server_info, "advert_stored_at", None)
    if isinstance(ts, (int, float)) and math.isfinite(float(ts)):
        age = min(max(now - float(ts), 0.0), LOAD_STALE_S)
    else:
        age = 0.0  # unstamped advert: treat as fresh (only repels traffic
        # from the advertiser itself, so assuming fresh is the safe side)
    weight = 1.0 - age / LOAD_STALE_S
    if weight <= 0.0:
        return 0.0
    delay = _finite_pos(load.get("delay_ms")) / 1000.0
    wait = load.get("decode_wait_ms") or load.get("wait_ms")
    if isinstance(wait, dict):
        delay = max(delay, _finite_pos(wait.get("p95")) / 1000.0)
    delay += _QUEUE_DEPTH_COST_S * min(
        _finite_pos(load.get("queue_depth")), 100.0
    )
    if load.get("shedding"):
        delay += LOAD_SHED_PENALTY_S
    return weight * min(delay, LOAD_DELAY_CAP_S)
