"""Gemma2-style family: sandwich norms, alternating sliding-window layers,
gated tanh-GELU MLP, logit soft-capping, sqrt(D) embedding scaling.

Reference: /root/reference/src/bloombee/models/gemma4/ (the reference's
"gemma4" additionally varies head_dim per layer type; uniform-head-dim
gemma2 models are covered here, heterogeneous head_dim is future work).
Gemma RMSNorm weights are stored as (w) with output x_norm * (1 + w); they
are converted to (1 + w) at load so the shared rms_norm applies.
"""

from __future__ import annotations

import math
from typing import Any


from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec

_NORMS = (
    "input_layernorm",
    "post_attention_layernorm",
    "pre_feedforward_layernorm",
    "post_feedforward_layernorm",
)


def gemma2_spec_from_hf(config: Any) -> ModelSpec:
    layer_types = getattr(config, "layer_types", None)
    if layer_types:
        pattern = tuple(
            "sliding" if "sliding" in t else "full" for t in layer_types
        )
    else:
        # HF Gemma2: even layers sliding, odd layers full
        pattern = ("sliding", "full")
    return ModelSpec(
        family="gemma2",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=config.head_dim,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 10000.0),
        tie_word_embeddings=True,
        layer_types=pattern,
        sliding_window=getattr(config, "sliding_window", 4096),
        attention_multiplier=getattr(config, "query_pre_attn_scalar", None)
        and getattr(config, "query_pre_attn_scalar") ** -0.5,
        logits_soft_cap=getattr(config, "final_logit_softcapping", 0.0) or 0.0,
        attn_logit_softcap=getattr(config, "attn_logit_softcapping", 0.0)
        or 0.0,
        embedding_multiplier=math.sqrt(config.hidden_size),
        mlp_type="gelu_tanh_gated",
        sandwich_norms=True,
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    p = f"model.layers.{layer_idx}"
    params = {}
    for ln in _NORMS:
        params[ln] = 1.0 + _t(reader, f"{p}.{ln}.weight", dtype)
    for proj in ("q", "k", "v", "o"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.self_attn.{proj}_proj.weight", dtype
        ).T
    for proj in ("gate", "up", "down"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.mlp.{proj}_proj.weight", dtype
        ).T
    return params


def _load_client(reader, dtype=None) -> dict:
    embed = _t(reader, "model.embed_tokens.weight", dtype)
    return {
        "embed": embed,
        "norm": 1.0 + _t(reader, "model.norm.weight", dtype),
        "lm_head": embed.T,
    }


register_family(
    Family(
        "gemma2", gemma2_spec_from_hf, loader=_load_block,
        client_loader=_load_client,
    )
)


# --------------------------------------------------------------- gemma3
def gemma3_spec_from_hf(config: Any) -> ModelSpec:
    """Gemma3 text tower: gemma2 structure + per-head q/k RMSNorm, no
    softcaps, and sliding layers roped with rope_local_base_freq.
    Multimodal gemma3 bundles nest the tower under text_config."""
    import dataclasses
    from types import SimpleNamespace

    text = getattr(config, "text_config", None)
    if text is not None:
        config = SimpleNamespace(**text) if isinstance(text, dict) else text
    base = gemma2_spec_from_hf(config)
    return dataclasses.replace(
        base,
        family="gemma3",
        qk_norm=True,
        logits_soft_cap=0.0,
        attn_logit_softcap=0.0,
        rope_theta=getattr(config, "rope_theta", 1_000_000.0),
        rope_local_theta=getattr(config, "rope_local_base_freq", 10_000.0),
        sliding_window=getattr(config, "sliding_window", 512),
    )


def _gemma3_prefix(reader) -> str:
    """Text-only checkpoints use model.*; multimodal bundles nest the tower
    under language_model.model.*."""
    if reader.has("model.embed_tokens.weight"):
        return "model"
    return "language_model.model"


def _load_block_gemma3(reader, layer_idx: int, dtype=None) -> dict:
    base = _gemma3_prefix(reader)
    p = f"{base}.layers.{layer_idx}"
    params = {}
    for ln in _NORMS:
        params[ln] = 1.0 + _t(reader, f"{p}.{ln}.weight", dtype)
    for proj in ("q", "k", "v", "o"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.self_attn.{proj}_proj.weight", dtype
        ).T
    for proj in ("gate", "up", "down"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.mlp.{proj}_proj.weight", dtype
        ).T
    params["q_norm"] = 1.0 + _t(
        reader, f"{p}.self_attn.q_norm.weight", dtype
    )
    params["k_norm"] = 1.0 + _t(
        reader, f"{p}.self_attn.k_norm.weight", dtype
    )
    return params


def _load_client_gemma3(reader, dtype=None) -> dict:
    base = _gemma3_prefix(reader)
    embed = _t(reader, f"{base}.embed_tokens.weight", dtype)
    return {
        "embed": embed,
        "norm": 1.0 + _t(reader, f"{base}.norm.weight", dtype),
        "lm_head": embed.T,
    }


for _name in ("gemma3", "gemma3_text"):
    register_family(
        Family(
            _name, gemma3_spec_from_hf, loader=_load_block_gemma3,
            client_loader=_load_client_gemma3,
        )
    )
