"""CacheManager admission control + host tiering.

Ports the intent of /root/reference/tests/test_cache.py (token budget,
blocking allocation, timeout) onto the asyncio single-process design.
"""

import asyncio
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import AllocationTimeout, CacheManager


def make_manager(**kw):
    defaults = dict(
        num_layers=2, num_pages=8, page_size=4, n_kv_heads=1, head_dim=4,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return CacheManager(**defaults)


def test_allocation_budget_and_release():
    async def run():
        m = make_manager()  # capacity 32 tokens
        async with m.allocate(batch_size=2, max_length=8) as h:
            assert m.tokens_left == 16
            assert h.batch_size == 2
            async with m.allocate(1, 16):
                assert m.tokens_left == 0
        assert m.tokens_left == 32
        assert m.table.free_pages == 8  # seqs dropped, pages freed

    asyncio.run(run())


def test_oversized_request_rejected():
    async def run():
        m = make_manager()
        with pytest.raises(AllocationTimeout):
            async with m.allocate(1, 33):
                pass

    asyncio.run(run())


def test_allocation_blocks_until_free():
    async def run():
        m = make_manager()
        order = []

        async def first():
            async with m.allocate(1, 32):
                order.append("first-in")
                await asyncio.sleep(0.05)
            order.append("first-out")

        async def second():
            await asyncio.sleep(0.01)
            async with m.allocate(1, 8):
                order.append("second-in")

        await asyncio.gather(first(), second())
        assert order == ["first-in", "first-out", "second-in"]

    asyncio.run(run())


def test_allocation_timeout():
    async def run():
        m = make_manager()
        async with m.allocate(1, 32):
            with pytest.raises(AllocationTimeout):
                async with m.allocate(1, 8, timeout=0.05):
                    pass

    asyncio.run(run())


def test_park_unpark_roundtrip():
    async def run():
        m = make_manager()
        rng = np.random.default_rng(0)
        async with m.allocate(1, 16) as h:
            sid = h.seq_ids[0]
            k_new = rng.normal(size=(6, 1, 4)).astype(np.float32)
            v_new = rng.normal(size=(6, 1, 4)).astype(np.float32)
            slots = jnp.asarray(m.write_slots(h, 6))
            for layer in range(m.num_layers):
                m.arena["k"] = (
                    m.arena["k"].at[layer, slots].set(jnp.asarray(k_new))
                )
                m.arena["v"] = (
                    m.arena["v"].at[layer, slots].set(jnp.asarray(v_new))
                )
            pages_before = m.table.free_pages
            m.park_sequence(sid)
            assert m.table.free_pages == pages_before + 2  # device pages freed
            m.unpark_sequence(sid)
            assert m.table.seq(sid).l_acc == 6
            got = np.asarray(
                m.arena["k"][0][jnp.asarray(m.table.prefix_slots(sid))]
            )
            np.testing.assert_array_equal(got, k_new)

    asyncio.run(run())


def test_async_park_survives_page_reuse():
    """Parking is async (pages free before the d2h copy lands): a second
    sequence immediately rewriting the freed slots must not corrupt the
    parked copy — the device executes the park's gather before the rewrite
    because it was dispatched first."""

    async def run():
        m = make_manager()
        rng = np.random.default_rng(7)
        async with m.allocate(1, 16) as h1, m.allocate(1, 16) as h2:
            sid = h1.seq_ids[0]
            k_new = rng.normal(size=(6, 1, 4)).astype(np.float32)
            slots = jnp.asarray(m.write_slots(h1, 6))
            for layer in range(m.num_layers):
                m.arena["k"] = (
                    m.arena["k"].at[layer, slots].set(jnp.asarray(k_new))
                )
                m.arena["v"] = (
                    m.arena["v"].at[layer, slots].set(jnp.asarray(k_new))
                )
            m.park_sequence(sid)
            # immediately claim + clobber the freed slots from a second seq
            # through a DONATING jit like the production step (step.py
            # donates the arena): on backends that honor donation this
            # rewrites the very buffer the in-flight park gather reads, so
            # dispatch order is what protects the parked copy (CPU ignores
            # donation, so there the clobber is only structural)
            slots2 = jnp.asarray(m.write_slots(h2, 6))

            @functools.partial(jax.jit, donate_argnums=(0,))
            def clobber(a, s):
                return a.at[:, s].set(999.0)

            m.arena["k"] = clobber(m.arena["k"], slots2)
            m.arena["v"] = clobber(m.arena["v"], slots2)
            m.unpark_sequence(sid)
            got = np.asarray(
                m.arena["k"][0][jnp.asarray(m.table.prefix_slots(sid))]
            )
            np.testing.assert_array_equal(got, k_new)

    asyncio.run(run())


def test_failed_park_copy_raises_parked_kv_lost(monkeypatch):
    """If the background d2h copy fails after pages were freed, the next
    touch of that sequence raises ParkedKVLost (clients replay the session)
    and the parked entry is dropped rather than wedged."""
    from bloombee_tpu.kv.cache_manager import ParkedKVLost

    async def run():
        m = make_manager()
        async with m.allocate(1, 16) as h:
            sid = h.seq_ids[0]
            m.write_slots(h, 6)
            monkeypatch.setattr(
                CacheManager,
                "_to_disk",
                lambda self, a, kind, seq_id: (_ for _ in ()).throw(
                    OSError("disk full")
                ),
            )
            m.park_sequence(sid, tier="disk")
            with pytest.raises(ParkedKVLost):
                m.unpark_sequence(sid)
            assert sid not in m._parked

    asyncio.run(run())


def test_park_to_disk_roundtrip(tmp_path, monkeypatch):
    """Disk tier (reference TorchDisk): parked KV lives in a memmap, device
    pages free, unpark restores exactly."""
    import jax.numpy as jnp

    from bloombee_tpu.kv import arena as arena_ops

    monkeypatch.setenv("BBTPU_DISK_DIR", str(tmp_path))

    async def run():
        m = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=8, dtype=jnp.float32,
        )
        rng = np.random.default_rng(0)
        async with m.allocate(1, 12) as handle:
            slots = m.write_slots(handle, 6)
            k_new = rng.normal(size=(6, 2, 8)).astype(np.float32)
            v_new = rng.normal(size=(6, 2, 8)).astype(np.float32)
            ak, av = arena_ops.arena_write(
                m.arena["k"][0], m.arena["v"][0],
                jnp.asarray(slots), jnp.asarray(k_new), jnp.asarray(v_new),
            )
            m.arena["k"] = m.arena["k"].at[0].set(ak)
            m.arena["v"] = m.arena["v"].at[0].set(av)
            sid = handle.seq_ids[0]
            before = np.asarray(m.arena["k"][0, slots])
            free_before = m.table.free_pages
            m.park_sequence(sid, tier="disk")
            assert m.table.free_pages > free_before  # pages actually freed
            parked_k = m._parked[sid].resolve()[0]
            assert isinstance(parked_k, np.memmap)
            m.unpark_sequence(sid)
            after = np.asarray(m.arena["k"][0, m.table.prefix_slots(sid)])
            np.testing.assert_array_equal(after, before)

    import asyncio

    asyncio.run(run())


def test_oversubscribed_sessions_park_and_resume(tmp_path):
    """Over-subscription (FlexGen serve-more-than-HBM-fits): two sessions
    whose reservations exceed physical pages are both admitted; page
    pressure parks the idle one's KV to host, and its next step unparks on
    demand — both generations stay token-exact vs HF."""
    import asyncio

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        # 5 pages x 4 tokens = 20 physical tokens; each session reserves 20
        # -> both admitted only via oversubscribe, and their live KV
        # (3 + 4 pages) cannot be co-resident; idle_park_s=0 parks eagerly
        s = BlockServer(
            model_uid="m", start=0, end=2, model_dir=d,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=5, page_size=4,
            oversubscribe=2.0, idle_park_s=0.0,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        ids_a = np.arange(6)[None, :] % config.vocab_size
        ids_b = (np.arange(6)[None, :] * 3 + 1) % config.vocab_size

        sess_a = model.inference_session(20, 1)
        sess_b = model.inference_session(20, 1)
        await sess_a.__aenter__()
        await sess_b.__aenter__()  # admitted only thanks to oversubscribe
        out_a = await model.generate(ids_a, max_new_tokens=4, session=sess_a)
        # B's steps pressure the pages -> A gets parked
        out_b = await model.generate(ids_b, max_new_tokens=8, session=sess_b)
        srv_sess_a = s._sessions[sess_a._spans[0].session_id]
        assert any(
            sid in s.manager._parked for sid in srv_sess_a.handle.seq_ids
        ), "idle session A was never parked"
        # A resumes: unparks on demand and continues exactly
        more_a = await model.generate(
            out_a[:, -1:], max_new_tokens=4, session=sess_a
        )
        await sess_a.__aexit__(None, None, None)
        await sess_b.__aexit__(None, None, None)

        full_a = np.concatenate([out_a, more_a[:, 1:]], axis=1)
        with torch.no_grad():
            pa = torch.tensor(ids_a)
            ref_a = hf.generate(pa, attention_mask=torch.ones_like(pa),
                                max_new_tokens=8, do_sample=False).numpy()
            pb = torch.tensor(ids_b)
            ref_b = hf.generate(pb, attention_mask=torch.ones_like(pb),
                                max_new_tokens=8, do_sample=False).numpy()
        # HF may stop early at its eos token; the common prefix must match
        n_a = min(full_a.shape[1], ref_a.shape[1])
        np.testing.assert_array_equal(full_a[:, :n_a], ref_a[:, :n_a])
        assert n_a > ids_a.shape[1] + 2
        n_b = min(out_b.shape[1], ref_b.shape[1])
        np.testing.assert_array_equal(out_b[:, :n_b], ref_b[:, :n_b])
        assert n_b > ids_b.shape[1] + 2

        await s.stop()
        await reg.stop()

    asyncio.run(run())
